"""Per-step anatomy: where does one engine step's wall time actually go?

The ROADMAP's largest open perf item — the AOT-compiled serving step —
cannot be judged without a number for the Python step-loop tax it exists
to kill.  This module decomposes EVERY engine step into:

* named **host segments**, measured as disjoint cursor intervals on the
  recorder's clock —

    ``schedule``       step planning (``SplitFuseScheduler.plan`` /
                       the serving frontend's KV-pressure preflight)
    ``draft_plan``     speculative draft planning (``_plan_drafts``)
    ``verify_plan``    verify-batch staging (history splice + ``pack``)
    ``aot_compile``    ahead-of-time ``lower().compile()`` work done
                       inside a step window (``warm_all`` invoked while
                       a step is open — deliberate warm-up, not a miss)
    ``compile_wait``   a dispatch that triggered a JIT cache miss — the
                       trace+compile ride the first call synchronously
    ``dispatch``       host-side dispatch of an already-compiled program
                       (batch packing, array staging, the jitted call's
                       enqueue)
    ``sample_accept``  host-side token fold (argmax accept loop, EOS/
                       limit checks, rollback truncation)
    ``overlap``        host work for step g+1 executed while step g was
                       still in flight on device (the async double-
                       buffered tick's scheduling/admission/delivery
                       window — loop tax HIDDEN under device time)
    ``bookkeeping``    everything else inside the step window (prefix-
                       cache publish, descriptor updates, the residual
                       between the last mark and step end)

* **device compute** — the blocking materialization of the dispatch's
  outputs on a real clock, or the explicitly charged virtual step cost
  (``charge_last_step``) under ``VirtualClock``/``ReplicaClockView``;

* the **host gap** — clock time between the previous step's end and this
  step's begin: the serving loop's admission/deadline/delivery work, the
  per-tick Python re-entry the AOT item wants amortized away.  Idle
  waits (``note_idle``) are excluded — idle is absent load, not loop
  tax — and the following step is flagged ``after_idle``.

The decomposition TILES by construction: every component is a
non-negative clock difference (or an explicit charge), and

    wall_s == host_gap_s + sum(host segments) + device_s

exactly, per step.  ``scripts/step_anatomy.py`` re-verifies the tiling
from the committed per-step table within 1e-6 (exit 1 on mismatch) —
the same trust-but-re-verify stance as ``why_slow.py``'s cause tiling.

A **compile tracker** rides along: every JIT cache miss the engine
reports (``note_compile``) is tagged warm-up or — after
:meth:`mark_steady` — an *unexpected steady-state recompile*, the
regression guard the AOT roadmap item will be held to (a serving step
set that recompiles mid-measurement is not AOT).

Overhead contract: the disabled path (:data:`NULL_ANATOMY`) allocates
NOTHING per call — one attribute read + one predicate per hook, pinned
by the tracemalloc test alongside :data:`~.trace.NULL_TRACER`.
Deliberately stdlib-only (no jax import): the engine imports it at
module scope and ``scripts/step_anatomy.py`` stays standalone.
"""

from collections import deque
from typing import Dict, List, Optional

from .trace import PerfClock

__all__ = ["HOST_SEGMENTS", "StepAnatomy", "NullStepAnatomy", "NULL_ANATOMY",
           "StepRecord", "CompileRecord"]

#: the closed host-segment vocabulary; every step exports all of them
#: (zero-filled) so the per-step table has one fixed shape
HOST_SEGMENTS = ("schedule", "draft_plan", "verify_plan", "aot_compile",
                 "compile_wait", "dispatch", "sample_accept", "overlap",
                 "bookkeeping", "promote_wait")


class StepRecord:
    """One recorded engine step (mutable only via the recorder)."""

    __slots__ = ("index", "path", "batch", "chunk", "segments", "device_s",
                 "host_gap_s", "wall_s", "after_idle", "compiles", "end_ts")

    def __init__(self, index: int):
        self.index = index
        self.path: Optional[str] = None      # decode|prefill|mixed|spec_verify|multi_decode
        self.batch: Optional[int] = None     # bucketed batch of the dispatch
        self.chunk: Optional[int] = None     # chunk width / verify width / fused k
        self.segments: Dict[str, float] = {s: 0.0 for s in HOST_SEGMENTS}
        self.device_s = 0.0
        self.host_gap_s = 0.0
        self.wall_s = 0.0
        self.after_idle = False
        self.compiles = 0                    # JIT cache misses THIS step paid for
        self.end_ts = 0.0                    # recorder-clock time at step end

    @property
    def shape_key(self) -> str:
        return f"{self.path}:b{self.batch}:c{self.chunk}"

    def host_s(self) -> float:
        return sum(self.segments.values())

    def to_row(self) -> dict:
        """Deterministic export row (9-dp rounding, sorted segment keys)."""
        return {
            "index": self.index,
            "path": self.path,
            "batch": self.batch,
            "chunk": self.chunk,
            "shape": self.shape_key,
            "segments": {s: round(self.segments[s], 9) for s in HOST_SEGMENTS},
            "device_s": round(self.device_s, 9),
            "host_gap_s": round(self.host_gap_s, 9),
            "wall_s": round(self.wall_s, 9),
            "after_idle": self.after_idle,
            "compiles": self.compiles,
        }


class CompileRecord:
    """One compile event: which program key, at which step, whether it
    fired after the warm-up boundary (``steady`` = the regression), and
    whether it was a deliberate AOT ``lower().compile()`` (``aot``)
    rather than a JIT cache miss a dispatch paid for synchronously."""

    __slots__ = ("key", "step_index", "steady", "ts", "aot")

    def __init__(self, key: str, step_index: int, steady: bool, ts: float,
                 aot: bool = False):
        self.key = key
        self.step_index = step_index
        self.steady = steady
        self.ts = ts
        self.aot = aot

    def to_row(self) -> dict:
        return {"key": self.key, "step_index": self.step_index,
                "steady": self.steady, "aot": self.aot,
                "ts": round(self.ts, 9)}


class StepAnatomy:
    """Per-step anatomy recorder with a pluggable clock.

    ``clock``: any ``now() -> float`` provider (``VirtualClock``,
    ``ReplicaClockView``, ``WallClock``, :class:`~.trace.PerfClock`
    default).  ``max_steps`` bounds the per-step table (deque; evictions
    counted in ``dropped_steps``); lifetime totals keep accumulating past
    the cap, so the host-gap-fraction gauges never lie about the window
    they cover being the whole run."""

    enabled = True

    def __init__(self, clock=None, max_steps: int = 4096):
        if max_steps < 1:
            raise ValueError(f"max_steps must be >= 1, got {max_steps}")
        self.clock = clock if clock is not None else PerfClock()
        self.steps = deque(maxlen=int(max_steps))
        self.dropped_steps = 0
        self.compiles: List[CompileRecord] = []
        self.steady_state_recompiles = 0
        #: monotonic count of CLOSED steps (deque eviction never rewinds it)
        self.total_steps = 0
        # lifetime totals (survive deque eviction; the cheap gauge inputs)
        self.total_wall_s = 0.0
        self.total_host_s = 0.0
        self.total_device_s = 0.0
        self.total_host_gap_s = 0.0
        self._steady = False
        self._last_end: Optional[float] = None
        self._after_idle = False
        self._cur: Optional[StepRecord] = None
        self._gap0 = 0.0        # inter-step gap captured at step_begin
        self._t = 0.0           # segment cursor

    # ------------------------------------------------------------- lifecycle

    def step_begin(self) -> None:
        """Open a step window.  Idempotent while a step is open: the
        serving frontend opens the window before its admission/preflight
        work and the engine's own ``step_begin`` then no-ops, so the two
        layers share one step without coordination."""
        if self._cur is not None:
            return
        t = self.clock.now()
        self._cur = StepRecord(self.total_steps)
        if self._last_end is not None:
            self._gap0 = t - self._last_end
            if self._gap0 < 0:   # clock-domain mixup must not corrupt tiling
                self._gap0 = 0.0
        else:
            self._gap0 = 0.0
        self._cur.after_idle = self._after_idle
        self._after_idle = False
        self._t = t

    def mark(self, segment: str) -> None:
        """Attribute the cursor interval ``[last mark, now]`` to
        ``segment`` and advance the cursor.  Outside an open step (a
        frontend early-return path) the call is a no-op."""
        cur = self._cur
        if cur is None:
            return
        t = self.clock.now()
        dt = t - self._t
        if dt > 0:
            cur.segments[segment] = cur.segments.get(segment, 0.0) + dt
        self._t = t

    def device_mark(self) -> None:
        """Attribute the cursor interval to device compute (the blocking
        output materialization on a real clock)."""
        cur = self._cur
        if cur is None:
            return
        t = self.clock.now()
        dt = t - self._t
        if dt > 0:
            cur.device_s += dt
        self._t = t

    def note_shape(self, path: str, batch: int, chunk: int) -> None:
        """Tag the open step with its dispatch shape — the per-(bucket,
        batch-shape) attribution key.  A step that never dispatches
        (empty plan) keeps ``path=None`` and is DISCARDED at step_end:
        its host time folds into the next real step's host gap, which is
        exactly what that time is (loop tax without device work)."""
        if self._cur is not None:
            self._cur.path = path
            self._cur.batch = int(batch)
            self._cur.chunk = int(chunk)

    def note_compile(self, key: str, aot: bool = False) -> None:
        """One compile event (the engine's ``_step_fns`` grew an entry).
        A JIT cache miss (``aot=False``) is tagged warm-up until
        :meth:`mark_steady`; after it, counted as an unexpected
        steady-state recompile — the AOT regression signal.  A deliberate
        ``warm_all`` AOT compile (``aot=True``) is NEVER steady-state
        noise: it is the warm-up mechanism itself, and does not bump the
        per-step JIT-miss counter either."""
        idx = self._cur.index if self._cur is not None else self.total_steps
        rec = CompileRecord(key, idx, self._steady and not aot,
                            self.clock.now(), aot=aot)
        self.compiles.append(rec)
        if self._cur is not None and not aot:
            self._cur.compiles += 1
        if rec.steady:
            self.steady_state_recompiles += 1

    def note_idle(self) -> None:
        """The driver idled (an arrival/deadline ``wait_until`` jump):
        exclude the idle stretch from the anatomy.  Between steps the gap
        origin resets (next step's host gap starts at 0, flagged
        ``after_idle``); inside an open step the cursor snaps to now so
        the jump lands in no segment."""
        if self._cur is not None:
            self._t = self.clock.now()
            self._cur.after_idle = True
        else:
            self._last_end = None
        self._after_idle = True

    def step_end(self) -> Optional[StepRecord]:
        """Close the step window: the residual cursor interval becomes
        ``bookkeeping``, the inter-step gap becomes ``host_gap_s``, and
        ``wall_s`` is the exact component sum (the tiling invariant).
        Returns the closed record, or None when the step never dispatched
        (discarded — see :meth:`note_shape`)."""
        cur = self._cur
        if cur is None:
            return None
        t = self.clock.now()
        tail = t - self._t
        if tail > 0:
            cur.segments["bookkeeping"] += tail
        self._cur = None
        if cur.path is None:
            # planned-but-empty step: keep the gap origin where it was so
            # this window folds into the next real step's host gap
            return None
        cur.host_gap_s = self._gap0
        cur.wall_s = cur.host_gap_s + cur.host_s() + cur.device_s
        cur.end_ts = t
        self._last_end = t
        self._retain(cur)
        return cur

    def charge_last_step(self, dt: float) -> Optional[StepRecord]:
        """Post-hoc device charge for clock-driven frontends: a
        ``VirtualClock``/``ReplicaClockView`` accounts the step cost via
        ``clock.on_step`` AFTER the engine step returned, so the serving
        loop forwards the charged seconds here.  The last record's device
        and wall grow by ``dt`` and the gap origin re-anchors at the
        clock's current reading (a VirtualClock just advanced by the
        charge; a deferred ReplicaClockView has not, and its round
        advance shows up in the next step's host gap — the round-
        quantization the fleet simulator actually imposes)."""
        if not dt >= 0:
            raise ValueError(f"step charge cannot be negative (dt={dt})")
        if not self.steps:
            return None
        rec = self.steps[-1]
        rec.device_s += dt
        rec.wall_s += dt
        self.total_device_s += dt
        self.total_wall_s += dt
        self._last_end = self.clock.now()
        rec.end_ts = self._last_end
        return rec

    def mark_steady(self) -> None:
        """Declare warm-up over: every later JIT cache miss is an
        unexpected steady-state recompile.  One-way by design — a harness
        that wants a fresh warm-up builds a fresh recorder."""
        self._steady = True

    @property
    def steady(self) -> bool:
        return self._steady

    def reset_steps(self) -> None:
        """Drop the per-step table and lifetime totals, keep the compile
        log and the steady boundary — the bench pattern: warm up, mark
        steady, reset, measure (warm-up steps must not dilute the
        measured host-gap fractions; warm-up COMPILES must stay on the
        record, they are what 'steady state' is defined against)."""
        self.steps.clear()
        self.dropped_steps = 0
        self.total_steps = 0
        self.total_wall_s = self.total_host_s = 0.0
        self.total_device_s = self.total_host_gap_s = 0.0
        self._last_end = None
        self._after_idle = False
        self._cur = None

    # --------------------------------------------------------------- intake

    def _retain(self, rec: StepRecord) -> None:
        if self.steps.maxlen is not None and len(self.steps) == self.steps.maxlen:
            self.dropped_steps += 1
        self.steps.append(rec)
        self.total_steps += 1
        self.total_wall_s += rec.wall_s
        self.total_host_s += rec.host_s()
        self.total_device_s += rec.device_s
        self.total_host_gap_s += rec.host_gap_s

    # -------------------------------------------------------------- queries

    @property
    def last_step(self) -> Optional[StepRecord]:
        return self.steps[-1] if self.steps else None

    def host_gap_fraction(self) -> Optional[float]:
        """Lifetime host-gap share of wall time — the one-number loop-tax
        gauge (None before the first step)."""
        if self.total_wall_s <= 0:
            return None
        return self.total_host_gap_s / self.total_wall_s

    def by_shape(self) -> Dict[str, dict]:
        """Per-(path, batch, chunk) aggregation over the RETAINED steps
        (the deque window; ``dropped_steps`` tells the reader when that
        window is not the whole run).  Deterministic key order."""
        out: Dict[str, dict] = {}
        for rec in self.steps:
            agg = out.get(rec.shape_key)
            if agg is None:
                agg = out[rec.shape_key] = {
                    "steps": 0, "wall_s": 0.0, "host_s": 0.0,
                    "device_s": 0.0, "host_gap_s": 0.0, "compiles": 0,
                    "segments": {s: 0.0 for s in HOST_SEGMENTS}}
            agg["steps"] += 1
            agg["wall_s"] += rec.wall_s
            agg["host_s"] += rec.host_s()
            agg["device_s"] += rec.device_s
            agg["host_gap_s"] += rec.host_gap_s
            agg["compiles"] += rec.compiles
            for s in HOST_SEGMENTS:
                agg["segments"][s] += rec.segments[s]
        for key in sorted(out):
            agg = out[key]
            wall = agg["wall_s"]
            rounded = {
                "steps": agg["steps"],
                "wall_s": round(wall, 9),
                "host_s": round(agg["host_s"], 9),
                "device_s": round(agg["device_s"], 9),
                "host_gap_s": round(agg["host_gap_s"], 9),
                "host_gap_fraction": round(agg["host_gap_s"] / wall, 6)
                if wall > 0 else None,
                "compiles": agg["compiles"],
                "segments": {s: round(agg["segments"][s], 9)
                             for s in HOST_SEGMENTS},
            }
            out[key] = rounded
        return {k: out[k] for k in sorted(out)}

    def summary(self) -> dict:
        return {
            "steps": self.total_steps,
            "retained_steps": len(self.steps),
            "dropped_steps": self.dropped_steps,
            "wall_s": round(self.total_wall_s, 9),
            "host_s": round(self.total_host_s, 9),
            "device_s": round(self.total_device_s, 9),
            "host_gap_s": round(self.total_host_gap_s, 9),
            "host_gap_fraction": None if self.total_wall_s <= 0
            else round(self.total_host_gap_s / self.total_wall_s, 6),
            "compiles": len(self.compiles),
            "steady_state_recompiles": self.steady_state_recompiles,
            "steady": self._steady,
        }

    def to_doc(self) -> dict:
        """The full deterministic export (what ``bench_serving.py
        --anatomy`` commits and ``scripts/step_anatomy.py`` re-verifies):
        per-step table, compile log, per-shape fold, summary.  Pure data,
        9-dp rounding, sorted keys downstream.  Schema 2 = the r20
        segment vocabulary (``aot_compile``/``overlap``) plus the
        compile log's ``aot`` flag."""
        return {
            "schema": 2,
            "summary": self.summary(),
            "by_shape": self.by_shape(),
            "steps": [rec.to_row() for rec in self.steps],
            "compiles": [c.to_row() for c in self.compiles],
        }

    # ------------------------------------------------------------ span lift

    def emit_spans(self, tracer, trace_id: Optional[int] = None,
                   track: str = "anatomy") -> int:
        """Lift the retained per-step records into tracer spans: one
        ``anatomy/step`` parent per step with its components laid
        end-to-end inside the window.  Naming contract: only
        ``host_gap`` and ``compile_wait`` — the two step-anatomy entries
        in the REQUEST-phase taxonomy (``trace_report.PHASES``,
        ``why_slow.CAUSES``) — emit as ``phase/<name>``; the plain host
        segments and device compute emit as ``anatomy/<name>``, which
        the request folds ignore by design.  So anatomy spans sharing a
        trace file with request traces never surface as ``unknown:<p>``:
        they either fold by name or are skipped, never half-parsed.
        Returns spans emitted; no-op (0) on a disabled tracer."""
        if not getattr(tracer, "enabled", False):
            return 0
        tid = trace_id if trace_id is not None else tracer.new_trace_id()
        n = 0
        for rec in self.steps:
            t0 = rec.end_ts - rec.wall_s
            parent = tracer.add_span(
                "anatomy/step", tid, t0, rec.end_ts, track=track,
                attrs={"shape": rec.shape_key, "compiles": rec.compiles,
                       "after_idle": rec.after_idle})
            n += 1
            t = t0
            parts = [("phase/host_gap", rec.host_gap_s)]
            parts += [("phase/compile_wait" if s == "compile_wait"
                       else f"anatomy/{s}", rec.segments[s])
                      for s in HOST_SEGMENTS]
            parts.append(("anatomy/device", rec.device_s))
            for name, dur in parts:
                if dur <= 0:
                    continue
                tracer.add_span(name, tid, t, t + dur,
                                parent_id=parent.span_id, track=track)
                t += dur
                n += 1
        return n


class NullStepAnatomy:
    """Disabled recorder: every hook is a no-op and allocates nothing —
    the engine hot path costs one attribute read + one predicate per
    step when anatomy is off (pinned by tracemalloc tests)."""

    enabled = False
    steps: tuple = ()
    compiles: tuple = ()
    dropped_steps = 0
    total_steps = 0
    steady_state_recompiles = 0
    steady = False

    def step_begin(self) -> None:
        pass

    def mark(self, segment) -> None:
        pass

    def device_mark(self) -> None:
        pass

    def note_shape(self, path, batch, chunk) -> None:
        pass

    def note_compile(self, key, aot=False) -> None:
        pass

    def note_idle(self) -> None:
        pass

    def step_end(self) -> None:
        return None

    def charge_last_step(self, dt) -> None:
        return None

    def mark_steady(self) -> None:
        pass

    def reset_steps(self) -> None:
        pass

    @property
    def last_step(self):
        return None

    def host_gap_fraction(self):
        return None

    def by_shape(self) -> dict:
        return {}

    def summary(self) -> dict:
        return {}

    def to_doc(self) -> dict:
        return {"schema": 2, "summary": {}, "by_shape": {}, "steps": [],
                "compiles": []}

    def emit_spans(self, tracer, trace_id=None, track="anatomy") -> int:
        return 0


NULL_ANATOMY = NullStepAnatomy()
