"""Windowed SLO burn-rate monitoring over per-tenant TTFT budgets.

A single violation counter cannot tell "we are burning the error budget
NOW" from "we burned it at 9am"; classic multi-window burn-rate alerting
(the SRE-workbook shape) fixes that with two windows: a FAST window that
reacts to onset and a SLOW window that confirms persistence — the alert
fires only when BOTH burn hot (one spike cannot page) and clears on the
fast window cooling (recovery is visible within one fast window).

``burn rate = (violating fraction in window) / error budget`` — 1.0 means
the tenant is consuming its budget exactly at the allowed rate; 10 means
ten times too fast.  The per-tenant SLO (``TenantSpec.ttft_slo``) and
budget (``TenantSpec.error_budget``) come straight from the tenancy
contract the router already enforces.

Mechanics: per (tenant, window) a rotating ring of ``sub_buckets`` time
buckets holding ``(n, bad)`` counts — O(sub_buckets) memory forever, no
sample retention (the same stance as the log-bucket histograms; the
coarser cousin :meth:`~.metrics.Histogram.window` exists for quantile
windows).  Everything is driven by the caller's clock: under
``VirtualClock`` the alert timeline — ``slo/alert_fired/<tenant>`` /
``slo/alert_cleared/<tenant>`` events, the :attr:`alerts` audit log, and
the flight-recorder ``ctrl/slo/<tenant>`` interval track — is
bit-reproducible across runs (the ``BENCH_ROUTER_ATTRIB.json`` receipt).
"""

import dataclasses
from typing import Dict, List, Optional

__all__ = ["BurnRateConfig", "SLOBurnMonitor"]


@dataclasses.dataclass(frozen=True)
class BurnRateConfig:
    #: fast window: reacts to onset (clock-seconds)
    fast_window: float = 8.0
    #: slow window: confirms persistence; must exceed the fast window
    slow_window: float = 32.0
    #: burn rate at/above which (on BOTH windows) the alert fires
    fire_threshold: float = 1.0
    #: fast-window burn rate at/below which an active alert clears
    #: (hysteresis: clear < fire, so a boundary burn cannot flap)
    clear_threshold: float = 0.5
    #: minimum requests in a window before its burn rate counts as
    #: evidence (an empty fleet must not alert on its first slow request)
    min_requests: int = 4
    #: time buckets per window (rotation granularity)
    sub_buckets: int = 8

    def __post_init__(self):
        if not 0 < self.fast_window < self.slow_window:
            raise ValueError(f"windows need 0 < fast < slow "
                             f"(got {self.fast_window}, {self.slow_window})")
        if not 0 <= self.clear_threshold < self.fire_threshold:
            raise ValueError(f"hysteresis needs clear < fire (got "
                             f"{self.clear_threshold}, {self.fire_threshold})")
        if self.sub_buckets < 2 or self.min_requests < 1:
            raise ValueError(f"sub_buckets >= 2 and min_requests >= 1 required "
                             f"(got {self.sub_buckets}, {self.min_requests})")


class _WindowRing:
    """Rotating (n, bad) time buckets covering one window."""

    __slots__ = ("span", "n", "bad", "idx", "start")

    def __init__(self, window: float, sub_buckets: int, t0: float):
        self.span = window / sub_buckets
        self.n = [0] * sub_buckets
        self.bad = [0] * sub_buckets
        self.idx = 0
        self.start = t0  # start time of the CURRENT bucket

    def advance(self, now: float) -> None:
        # rotate whole buckets; a jump past the entire window zeroes it in
        # at most len(n) steps (cheap and allocation-free)
        steps = 0
        while now >= self.start + self.span and steps < 2 * len(self.n):
            self.idx = (self.idx + 1) % len(self.n)
            self.n[self.idx] = 0
            self.bad[self.idx] = 0
            self.start += self.span
            steps += 1
        if now >= self.start + self.span:  # still behind: clamp the anchor
            for i in range(len(self.n)):
                self.n[i] = self.bad[i] = 0
            self.start = now

    def observe(self, now: float, bad: bool) -> None:
        self.advance(now)
        self.n[self.idx] += 1
        if bad:
            self.bad[self.idx] += 1

    def totals(self) -> (int, int):
        return sum(self.n), sum(self.bad)


class SLOBurnMonitor:
    """Multi-window burn-rate alerting over ``TenantSpec.ttft_slo``.

    ``tenants`` is the router's :class:`~..serving.fleet.tenancy.
    TenantRegistry`; only tenants with a ``ttft_slo`` are monitored.
    ``emit(name, value)`` is the router's monitor emitter; ``metrics`` an
    optional MetricsRegistry for the ``slo/burn_fast/<tenant>`` gauges;
    ``recorder`` an optional flight recorder for the alert-window
    intervals.  Call :meth:`observe` per DONE request and :meth:`tick`
    once per fleet round."""

    def __init__(self, tenants, config: BurnRateConfig = None, clock=None,
                 emit=None, metrics=None, recorder=None):
        self.tenants = tenants
        self.config = config or BurnRateConfig()
        self.clock = clock
        self._emit_cb = emit
        self.metrics = metrics
        self.recorder = recorder
        self._fast: Dict[str, _WindowRing] = {}
        self._slow: Dict[str, _WindowRing] = {}
        self._active: Dict[str, bool] = {}
        #: the audit log: one dict per alert episode —
        #: {"tenant", "fired_ts", "cleared_ts" (None while active),
        #:  "fired_fast", "fired_slow"} in firing order
        self.alerts: List[dict] = []
        self.observed = 0

    # ------------------------------------------------------------- plumbing

    def bind(self, emit=None, metrics=None, recorder=None) -> None:
        """Late wiring (the router attaches its own emitter/registry)."""
        if emit is not None:
            self._emit_cb = emit
        if metrics is not None:
            self.metrics = metrics
        if recorder is not None:
            self.recorder = recorder

    def _now(self, ts: Optional[float]) -> float:
        if ts is not None:
            return ts
        if self.clock is None:
            raise ValueError("SLOBurnMonitor needs a clock or explicit ts")
        return self.clock.now()

    def _rings(self, tenant: str, now: float):
        fast = self._fast.get(tenant)
        if fast is None:
            cfg = self.config
            fast = self._fast[tenant] = _WindowRing(cfg.fast_window,
                                                    cfg.sub_buckets, now)
            self._slow[tenant] = _WindowRing(cfg.slow_window,
                                             cfg.sub_buckets, now)
            self._active[tenant] = False
            if self.recorder is not None:
                self.recorder.note_state(f"ctrl/slo/{tenant}", "ok", now)
        return fast, self._slow[tenant]

    # --------------------------------------------------------------- intake

    def observe(self, tenant: str, ttft: Optional[float],
                now: Optional[float] = None) -> None:
        """Fold one completed request's TTFT against its tenant's SLO.
        Tenants without a ``ttft_slo`` (and requests without a TTFT) are
        ignored — deadline accounting already covers them."""
        spec = self.tenants.spec(tenant)
        if spec.ttft_slo is None or ttft is None:
            return
        t = self._now(now)
        fast, slow = self._rings(tenant, t)
        bad = ttft > spec.ttft_slo
        fast.observe(t, bad)
        slow.observe(t, bad)
        self.observed += 1

    # ----------------------------------------------------------------- tick

    def burn_rates(self, tenant: str, now: Optional[float] = None):
        """``(fast, slow)`` burn rates right now; windows with fewer than
        ``min_requests`` observations read 0.0 (insufficient evidence)."""
        t = self._now(now)
        if tenant not in self._fast:
            return 0.0, 0.0
        spec = self.tenants.spec(tenant)
        budget = max(1e-9, spec.error_budget)
        out = []
        for ring in (self._fast[tenant], self._slow[tenant]):
            ring.advance(t)
            n, bad = ring.totals()
            out.append(0.0 if n < self.config.min_requests
                       else (bad / n) / budget)
        return out[0], out[1]

    def tick(self, now: Optional[float] = None) -> None:
        """One control round: advance every tenant's windows, publish the
        burn gauges, and run the hysteresis-gated alert transitions."""
        t = self._now(now)
        cfg = self.config
        for tenant in sorted(self._fast):
            fast, slow = self.burn_rates(tenant, t)
            if self.metrics is not None:
                self.metrics.gauge(f"slo/burn_fast/{tenant}").set(round(fast, 9))
                self.metrics.gauge(f"slo/burn_slow/{tenant}").set(round(slow, 9))
            active = self._active[tenant]
            if not active and fast >= cfg.fire_threshold \
                    and slow >= cfg.fire_threshold:
                self._active[tenant] = True
                self.alerts.append({"tenant": tenant, "fired_ts": round(t, 9),
                                    "cleared_ts": None,
                                    "fired_fast": round(fast, 9),
                                    "fired_slow": round(slow, 9)})
                if self._emit_cb is not None:
                    self._emit_cb(f"slo/alert_fired/{tenant}", fast)
                if self.recorder is not None:
                    self.recorder.note_state(f"ctrl/slo/{tenant}", "alert", t,
                                             attrs={"fast": round(fast, 9),
                                                    "slow": round(slow, 9)})
            elif active and fast <= cfg.clear_threshold:
                self._active[tenant] = False
                for a in reversed(self.alerts):
                    if a["tenant"] == tenant and a["cleared_ts"] is None:
                        a["cleared_ts"] = round(t, 9)
                        break
                if self._emit_cb is not None:
                    self._emit_cb(f"slo/alert_cleared/{tenant}", fast)
                if self.recorder is not None:
                    self.recorder.note_state(f"ctrl/slo/{tenant}", "ok", t)

    # -------------------------------------------------------------- queries

    def active(self, tenant: str) -> bool:
        return self._active.get(tenant, False)

    def summary(self) -> dict:
        return {
            "config": {
                "fast_window": self.config.fast_window,
                "slow_window": self.config.slow_window,
                "fire_threshold": self.config.fire_threshold,
                "clear_threshold": self.config.clear_threshold,
                "min_requests": self.config.min_requests,
            },
            "observed": self.observed,
            "tenants": sorted(self._fast),
            "active": sorted(t for t, a in self._active.items() if a),
            "alerts": [dict(a) for a in self.alerts],
        }
