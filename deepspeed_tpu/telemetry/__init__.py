"""Telemetry: deterministic distributed tracing + always-on metrics.

The cross-cutting observability layer over the whole stack
(docs/OBSERVABILITY.md): a span :mod:`tracer <.trace>` whose timestamps
come from the pluggable serving clock (bit-reproducible traces under
``VirtualClock``), Chrome-trace/Perfetto + JSONL :mod:`exporters
<.export>` with atomic writes, and a :mod:`metrics <.metrics>` registry
(counters / gauges / fixed-log-bucket histograms) bridged into
``MonitorMaster`` as ``telemetry/*`` events.

Instrumented surfaces: engine step phases (fwd/bwd/optim and the
streamed-optimizer upload/compute/download pipeline), the serving
request lifecycle (one trace per request, preemptions as span events),
and fleet dispatch (the client trace_id survives replica failover).
"""

from .export import (load_chrome_trace, spans_to_jsonl, to_chrome_trace,
                     write_chrome_trace, write_jsonl)
from .flight_recorder import FlightRecorder
from .metrics import (Counter, Gauge, Histogram, HistogramWindow,
                      MetricsRegistry)
from .slo import BurnRateConfig, SLOBurnMonitor
from .spans import PHASE_OF_STATE, emit_attempt_spans, phase_intervals
from .step_anatomy import (HOST_SEGMENTS, NULL_ANATOMY, NullStepAnatomy,
                           StepAnatomy)
from .trace import (NULL_SPAN, NULL_TRACER, NullTracer, PerfClock, Span,
                    Tracer)

__all__ = [
    "load_chrome_trace", "spans_to_jsonl", "to_chrome_trace",
    "write_chrome_trace", "write_jsonl",
    "FlightRecorder",
    "Counter", "Gauge", "Histogram", "HistogramWindow", "MetricsRegistry",
    "BurnRateConfig", "SLOBurnMonitor",
    "PHASE_OF_STATE", "emit_attempt_spans", "phase_intervals",
    "HOST_SEGMENTS", "NULL_ANATOMY", "NullStepAnatomy", "StepAnatomy",
    "NULL_SPAN", "NULL_TRACER", "NullTracer", "PerfClock", "Span", "Tracer",
]
