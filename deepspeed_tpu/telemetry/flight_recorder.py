"""Fleet flight recorder: a bounded, always-on span/event ring with
crash-scoped Chrome-trace dumps.

The r10 tracer answers "what did this REQUEST do" — one trace per unit of
work, exported at end of run.  The flight recorder answers the question an
operator has at 3am: "what was the whole CONTROL PLANE doing in the
seconds before replica 3 got fenced?"  Its design constraints are the
opposite of the tracer's:

* **bounded, always-on** — a per-track ring (``deque(maxlen=...)``) keeps
  only the last N finished spans per track, so it can run forever on a
  wall-clock server at O(tracks x N) memory; evictions are counted per
  track in :attr:`dropped`, never hidden;
* **crash-scoped dumps** — :meth:`maybe_dump` atomically writes a
  Chrome-trace snapshot of the rings (open state intervals closed at the
  dump instant *in the export only*) when something went wrong: a replica
  death, a fencing episode, an output divergence.  The dump is the black
  box an operator pulls after the incident — hence "flight recorder";
* **clock-pluggable and deterministic** — timestamps come from the caller
  (or the attached serving clock), so under ``VirtualClock`` dumps are
  byte-identical across runs, exactly like the r10 trace artifacts.

What lands in the rings (docs/OBSERVABILITY.md "Flight recorder"):

* every finished span of an attached :class:`~.trace.Tracer` (the
  recorder is a retention *sink*: ``Tracer(recorder=...)`` mirrors spans
  into the ring as they finish, so request phase spans survive in the
  ring even after the tracer's own retention drops them);
* control-plane message spans from
  :class:`~..serving.fleet.transport.ControlTransport` — one
  ``ctrl/<kind>`` span per DELIVERED message spanning send→deliver (the
  causal pair), one ``ctrl/drop`` instant per message the fabric ate
  (cause: loss / partition / fault), on per-link ``ctrl/link/...``
  tracks;
* lease-lifecycle intervals (``ctrl/lease/<state>`` per replica), brownout
  rung occupancy (``ctrl/overload/<rung>``), and autoscaler decision
  instants (``ctrl/autoscale/<action>``) via :meth:`note_state` /
  :meth:`instant`.
"""

import dataclasses
from collections import deque
from typing import Dict, List, Optional, Tuple

from .trace import Span

__all__ = ["FlightRecorder"]


@dataclasses.dataclass
class _OpenState:
    """One track's currently-open interval (note_state)."""
    name: str
    since: float
    attrs: Optional[dict]


class FlightRecorder:
    """Bounded per-track ring of finished spans + interval/instant intake.

    ``clock`` is any ``now()`` provider (the fleet's shared clock) used
    when a caller passes no timestamp; ``max_per_track`` bounds every
    ring; ``dump_dir`` enables :meth:`maybe_dump` (None = ring only, no
    files — the always-on default costs no I/O)."""

    def __init__(self, clock=None, max_per_track: int = 256,
                 dump_dir: Optional[str] = None):
        if max_per_track < 1:
            raise ValueError(f"max_per_track must be >= 1, got {max_per_track}")
        self.clock = clock
        self.max_per_track = int(max_per_track)
        self.dump_dir = dump_dir
        self._tracks: Dict[str, deque] = {}
        #: per-track count of spans the ring evicted (bounded-memory receipt)
        self.dropped: Dict[str, int] = {}
        self._open: Dict[str, _OpenState] = {}
        #: recorder-local monotonic span ids (disjoint id space from any
        #: attached tracer is fine: dumps carry whole spans, not id refs)
        self._next_id = 1
        self.dumps = 0
        self.dump_log: List[Tuple[str, float, str]] = []  # (reason, ts, path)

    # --------------------------------------------------------------- intake

    def _now(self, ts: Optional[float]) -> float:
        if ts is not None:
            return ts
        if self.clock is None:
            raise ValueError("FlightRecorder needs an explicit ts when "
                             "constructed without a clock")
        return self.clock.now()

    def _ring(self, track: str) -> deque:
        ring = self._tracks.get(track)
        if ring is None:
            ring = self._tracks[track] = deque(maxlen=self.max_per_track)
            self.dropped[track] = 0
        return ring

    def _retain(self, span: Span) -> None:
        ring = self._ring(span.track)
        if len(ring) == ring.maxlen:
            self.dropped[span.track] += 1  # the deque evicts the oldest
        ring.append(span)

    def observe(self, span: Span) -> None:
        """Tracer retention sink: mirror one FINISHED span into the ring
        (``Tracer(recorder=...)`` calls this from ``_retain``)."""
        self._retain(span)

    def span(self, name: str, track: str, start_ts: float, end_ts: float,
             attrs: Optional[dict] = None) -> Span:
        """Record one finished span directly (control-plane message pairs)."""
        s = Span(name, 0, self._next_id, None, track, start_ts, attrs)
        self._next_id += 1
        s.end_ts = max(end_ts, start_ts)
        self._retain(s)
        return s

    def instant(self, name: str, track: str, ts: Optional[float] = None,
                attrs: Optional[dict] = None) -> Span:
        """Record a point event (zero-width span: renders as a Perfetto
        zero-duration slice, keeps the exporter/validator contract)."""
        t = self._now(ts)
        return self.span(name, track, t, t, attrs)

    def note_state(self, track: str, name: str, ts: Optional[float] = None,
                   attrs: Optional[dict] = None) -> None:
        """Interval intake for state machines: close the track's currently
        open interval at ``ts`` (materializing it into the ring) and open
        ``name``.  The first call on a track only opens.  Lease states,
        brownout rungs and SLO alert windows all land through here."""
        t = self._now(ts)
        cur = self._open.get(track)
        if cur is not None:
            if cur.name == name:
                return  # no transition: the open interval keeps running
            self.span(cur.name, track, cur.since, t, cur.attrs)
        self._open[track] = _OpenState(name=name, since=t, attrs=dict(attrs) if attrs else None)

    # ----------------------------------------------------------------- dump

    def snapshot_spans(self, now: Optional[float] = None) -> List[Span]:
        """Every retained span plus the open intervals closed at ``now``
        (export-only: the open state itself is not mutated).  Ordered by
        (track, start_ts, id) for deterministic export."""
        t = self._now(now)
        spans: List[Span] = []
        for track in sorted(self._tracks):
            spans.extend(self._tracks[track])
        for track in sorted(self._open):
            cur = self._open[track]
            s = Span(cur.name, 0, 0, None, track, cur.since,
                     dict(cur.attrs) if cur.attrs else {"open": True})
            s.attrs.setdefault("open", True)
            s.end_ts = max(t, cur.since)
            spans.append(s)
        return spans

    def maybe_dump(self, reason: str, now: Optional[float] = None,
                   meta: Optional[dict] = None) -> Optional[str]:
        """Atomically write a crash-scoped Chrome trace of the rings; the
        file is ``flight_<seq>_<reason>.json`` under ``dump_dir``.  Returns
        the path, or None when no ``dump_dir`` is configured (ring-only
        mode) — callers emit the ``recorder/dump`` event only on a real
        dump.  Never raises into the caller's failure path by design
        CHOICE of the caller (the router guards it): a failed black-box
        write must not turn a replica death into a driver death."""
        t = self._now(now)
        if self.dump_dir is None:
            return None
        import os

        from ..resilience.atomic_io import atomic_write_bytes
        from .export import _dump, to_chrome_trace
        # a black box that silently can't write is worse than none: make
        # the dump dir on first use so a not-yet-created path still dumps
        os.makedirs(self.dump_dir, exist_ok=True)
        seq = self.dumps + 1
        safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
        path = os.path.join(self.dump_dir, f"flight_{seq:03d}_{safe}.json")
        doc = to_chrome_trace(
            self.snapshot_spans(t),
            dropped_spans=sum(self.dropped.values()),
            meta={"recorder": "flight", "reason": reason,
                  "dump_ts": round(t, 9), "dump_seq": seq,
                  "dropped_per_track": ", ".join(
                      f"{k}={v}" for k, v in sorted(self.dropped.items()) if v),
                  **(meta or {})})
        atomic_write_bytes(path, _dump(doc))
        # counted only once the file exists: a failed write must not
        # desync the cumulative recorder/dump event from the files on disk
        self.dumps = seq
        self.dump_log.append((reason, t, path))
        return path

    # -------------------------------------------------------------- queries

    @property
    def n_spans(self) -> int:
        return sum(len(r) for r in self._tracks.values())

    def track(self, name: str) -> List[Span]:
        return list(self._tracks.get(name, ()))

    def summary(self) -> dict:
        return {
            "tracks": {k: len(r) for k, r in sorted(self._tracks.items())},
            "dropped": {k: v for k, v in sorted(self.dropped.items()) if v},
            "open": {k: self._open[k].name for k in sorted(self._open)},
            "max_per_track": self.max_per_track,
            "dumps": self.dumps,
        }
