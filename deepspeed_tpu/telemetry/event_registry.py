"""Central registry of every monitor/telemetry event name in the stack.

Until r11 the event taxonomy lived in three places that drifted
independently: the emitter call sites (``events.emit``, ``_emit``,
``metrics.counter/gauge/histogram``), the docs/OBSERVABILITY.md table, and
reviewers' heads.  This module is now the single source of truth:

* the ``event-registry`` dslint checker validates every event-name
  literal in the package against :data:`EVENTS` / :data:`DYNAMIC`
  (an emitter using an unregistered name fails tier-1);
* the event table in docs/OBSERVABILITY.md is GENERATED from here
  (``python deepspeed_tpu/telemetry/event_registry.py --sync
  docs/OBSERVABILITY.md``) and the same checker fails when the committed
  doc block differs from :func:`render_event_table` — docs cannot drift.

Deliberately stdlib-only with no package-relative imports: dslint loads it
standalone (no jax import) and it runs directly by path.

``kind`` vocabulary: ``event`` (a monitor ``write_events`` tuple),
``counter``/``gauge``/``histogram`` (MetricsRegistry instruments — note
histograms additionally fan out over the ``telemetry/`` bridge as
``_p50/_p95/_p99/_count``), and — since r18 — ``span``/``track``
(flight-recorder span names and track names: not monitor events, but the
same one-namespace discipline applies, so the dslint checker validates
their literals here too).
"""

import re

#: static event names: one entry per literal an emitter uses
EVENTS = {
    # ---- resilience bus (resilience/events.py -> monitor forward)
    "resilience/fault_injected": ("event", "resilience/fault_injection.py",
                                  "a planned fault fired at a site"),
    "resilience/retry": ("event", "resilience/retry.py",
                         "transient failure absorbed; backing off"),
    "resilience/retry_exhausted": ("event", "resilience/retry.py",
                                   "retry budget/schedule spent; re-raising"),
    "resilience/admission_retry": ("event", "resilience/retry.py",
                                   "serving admission backoff probe"),
    "resilience/watchdog_hang": ("event", "resilience/watchdog.py",
                                 "step exceeded the hang threshold"),
    "resilience/rendezvous": ("event", "elasticity/elastic_agent.py",
                              "elastic agent re-rendezvous after a loss"),
    "resilience/device_loss": ("event", "elasticity/elastic_agent.py",
                               "DEVICE_LOST-class failure classified"),
    "resilience/ckpt_published": ("event", "checkpoint/engine.py",
                                  "'latest' atomically points at a new tag"),
    "resilience/ckpt_invalid_tag": ("event", "checkpoint/engine.py",
                                    "requested tag failed validation"),
    "resilience/ckpt_fallback": ("event", "checkpoint/engine.py",
                                 "auto-fallback to the newest valid tag"),
    "resilience/ckpt_retention_delete": ("event", "checkpoint/engine.py",
                                         "keep-last-K pruned a tag"),
    "resilience/host_opt_reject": ("event",
                                   "runtime/swap_tensor/host_streamed_optimizer.py",
                                   "host-tier npz failed manifest/crc checks"),
    # ---- serving frontend (serving/engine.py)
    "serving/rejected": ("event+counter", "serving/engine.py",
                         "admission rejected a request"),
    "serving/preempted": ("event", "serving/engine.py",
                          "KV pressure evicted + requeued a request"),
    "serving/e2e_latency": ("event", "serving/engine.py",
                            "terminal request end-to-end seconds"),
    "serving/preemptions": ("event+counter", "serving/engine.py",
                            "preemption count of a terminal request"),
    "serving/ttft": ("event", "serving/engine.py", "time to first token"),
    "serving/tpot": ("event", "serving/engine.py", "time per output token"),
    "serving/queue_wait": ("event", "serving/engine.py",
                           "admission-queue wait of a DONE request"),
    "serving/deadline_met": ("event", "serving/engine.py",
                             "1/0: DONE request met its SLA deadline"),
    "serving/timed_out": ("event", "serving/engine.py",
                          "request expired its deadline"),
    "serving/submitted": ("counter", "serving/engine.py",
                          "requests entering submit()"),
    "serving/e2e_s": ("histogram", "serving/engine.py",
                      "end-to-end seconds, all terminal requests"),
    "serving/ttft_s": ("histogram", "serving/engine.py",
                       "time to first token, DONE requests"),
    "serving/tpot_s": ("histogram", "serving/engine.py",
                       "time per output token, DONE requests"),
    "serving/queue_wait_s": ("histogram", "serving/engine.py",
                             "admission-queue wait, DONE requests"),
    # ---- speculative decoding (serving/engine.py folding
    #      inference/v2/engine_v2.py last_spec_round)
    "spec/proposed": ("counter", "serving/engine.py",
                      "draft tokens fed to verify dispatches"),
    "spec/accepted": ("counter", "serving/engine.py",
                      "draft tokens the verify argmax confirmed"),
    "spec/rollback_pages": ("counter", "serving/engine.py",
                            "KV pages released rolling back rejected drafts"),
    "spec/acceptance_rate": ("histogram", "serving/engine.py",
                             "per-verify-round accepted/proposed ratio"),
    # ---- KV migration (serving/kvtransfer/ via serving/engine.py)
    "serving/migrated": ("event+counter", "serving/engine.py",
                         "request handed off to another replica with its KV"),
    "migration/kv_imports": ("counter", "serving/engine.py",
                             "KV-import fast-path resumes (no prompt recompute)"),
    "migration/import_fallback": ("counter", "serving/engine.py",
                                  "snapshot rejected at import -> "
                                  "recompute-on-resume"),
    # ---- fleet prefix directory (serving/fleet/prefix_directory.py +
    #      router.py + serving/engine.py)
    "prefix/publish": ("counter", "serving/fleet/prefix_directory.py",
                       "replica published a prefix-chain digest to the "
                       "fleet directory"),
    "prefix/evict": ("counter", "serving/fleet/prefix_directory.py",
                     "replica retracted a digest (cache eviction) from "
                     "the directory"),
    "prefix/import": ("counter", "serving/engine.py",
                      "hot-prefix KV pages adopted into this replica's "
                      "cache (cold-replica warm-up fast path)"),
    "prefix/import_fallback": ("counter", "serving/fleet/router.py",
                               "prefix import rejected/failed -> cold "
                               "dispatch, prefill recomputes"),
    "fleet/prefix_import": ("event", "serving/fleet/router.py",
                            "cold-replica prefix KV import completed "
                            "before dispatch (value = target rid)"),
    "fleet/prefix_import_fallback": ("event", "serving/fleet/router.py",
                                     "prefix import abandoned; the "
                                     "dispatch proceeds cold"),
    "fleet/prefix_directory_entries": ("gauge", "serving/fleet/router.py",
                                       "(rid, digest) entries resident in "
                                       "the fleet prefix directory, "
                                       "sampled once per fleet round"),
    # ---- fleet router (serving/fleet/)
    "fleet/dispatch": ("event", "serving/fleet/router.py",
                       "request placed on a replica (value = rid)"),
    "fleet/session_park": ("event", "serving/fleet/router.py",
                           "session turn parked mid-generation for a tool "
                           "stall (KV demoted host-side, serving/sessions)"),
    "fleet/session_resume": ("event", "serving/fleet/router.py",
                             "parked session turn resumed in place (tool "
                             "result arrived; staged KV promotes back)"),
    "fleet/replica_dead": ("event", "serving/fleet/router.py",
                           "replica declared dead (value = rid)"),
    "fleet/failover_requeued": ("event", "serving/fleet/router.py",
                                "in-flight requests displaced to survivors"),
    "fleet/migration_start": ("event", "serving/fleet/router.py",
                              "KV export began on a prefill replica "
                              "(value = source rid)"),
    "fleet/migration_complete": ("event", "serving/fleet/router.py",
                                 "snapshot handed off to a decode replica "
                                 "(value = source rid)"),
    "fleet/migration_fallback": ("event", "serving/fleet/router.py",
                                 "migration abandoned; recompute/in-place "
                                 "decode owns the request"),
    # ---- control-plane transport (serving/fleet/transport.py +
    #      health.py + router.py) — docs/SERVING.md "Control-plane
    #      transport"; the per-counter transport/* family is DYNAMIC
    "fleet/lease_suspect": ("event", "serving/fleet/health.py",
                            "heartbeat silence passed suspect_after; no "
                            "new dispatches (value = rid)"),
    "fleet/lease_expired": ("event", "serving/fleet/health.py",
                            "lease expired: fleet-declared death, work "
                            "re-dispatched, dispatch epoch bumped "
                            "(value = rid)"),
    "fleet/lease_renewed": ("event", "serving/fleet/health.py",
                            "heartbeats resumed (SUSPECT healed, or a "
                            "fenced replica rejoined) (value = rid)"),
    "fleet/fenced_replica": ("event", "serving/fleet/router.py",
                             "a fleet-dead replica heartbeated again; a "
                             "FENCE is in flight (value = rid)"),
    "fleet/fenced_request": ("event", "serving/fleet/router.py",
                             "in-flight zombie requests cancelled by a "
                             "fence (value = count)"),
    "fleet/fenced_completion": ("event", "serving/fleet/router.py",
                                "late zombie completions discarded by "
                                "fencing — never double-served "
                                "(value = count)"),
    "prefix/publish_gap": ("event", "serving/fleet/router.py",
                           "a sequence gap in a replica's prefix-publish "
                           "stream was declared lost (value = rid)"),
    "prefix/resync": ("event", "serving/fleet/router.py",
                      "full-digest directory resync applied for a replica "
                      "(value = rid)"),
    "fleet/prefix_warmup": ("event", "serving/fleet/router.py",
                            "directory-driven warm-up pre-imported hot "
                            "chains onto a recovering replica "
                            "(value = rid)"),
    "fleet/lease_resize": ("event", "serving/fleet/health.py",
                           "adaptive lease sizing widened/tightened a "
                           "replica's lease band from observed link "
                           "quality (value = rid)"),
    "fleet/lifecycle_cmd": ("event", "serving/fleet/router.py",
                            "a typed lifecycle command (recover/drain/"
                            "park/restart/role_change/mig_complete) was "
                            "issued over the control transport "
                            "(value = target rid)"),
    "fleet/role_change": ("event", "serving/fleet/router.py",
                          "a drained replica's serving role was "
                          "reassigned (prefill/decode/mixed) "
                          "(value = rid)"),
    # ---- overload control plane (serving/fleet/autoscale.py + router.py)
    "fleet/scale_up": ("event", "serving/fleet/autoscale.py",
                       "autoscaler provisioned a replica through "
                       "RECOVERING (value = rid)"),
    "fleet/scale_drain": ("event", "serving/fleet/autoscale.py",
                          "scale-down drain began; no new dispatches "
                          "(value = rid)"),
    "fleet/scale_down": ("event", "serving/fleet/autoscale.py",
                         "drained replica parked idle — nothing in "
                         "flight was killed (value = rid)"),
    "fleet/overload_step_up": ("event", "serving/fleet/autoscale.py",
                               "degradation ladder stepped up "
                               "(value = new rung)"),
    "fleet/overload_step_down": ("event", "serving/fleet/autoscale.py",
                                 "degradation ladder stepped down "
                                 "(value = new rung)"),
    "fleet/overload_shed": ("event", "serving/fleet/router.py",
                            "best-effort admission shed with a "
                            "retry-after hint (value = rung)"),
    "fleet/kv_quota_reject": ("event", "serving/fleet/router.py",
                              "admission or prefix-import rejected "
                              "against a tenant's KV page quota "
                              "(value = projected pages)"),
    "fleet/serving_replicas": ("gauge", "serving/fleet/router.py",
                               "replicas in a serving state, sampled "
                               "once per fleet round"),
    "fleet/overload_rung": ("gauge", "serving/fleet/router.py",
                            "current degradation-ladder rung (0 = "
                            "normal service)"),
    # ---- flight recorder (telemetry/flight_recorder.py, driven by
    #      serving/fleet/router.py; docs/OBSERVABILITY.md "Flight recorder")
    "recorder/dump": ("event", "serving/fleet/router.py",
                      "crash-scoped flight-recorder trace dumped (replica "
                      "death / lease expiry / fencing / divergence; value = "
                      "cumulative dump count)"),
    # ---- control-plane flight-recorder spans/tracks: names the recorder
    #      rings use (causal message spans ride the DYNAMIC ctrl/ family)
    "ctrl/drop": ("span", "serving/fleet/transport.py",
                  "recorder instant: the fabric ate a control message "
                  "(attrs: kind, seq, mid, cause = loss|partition|"
                  "send_fault|deliver_fault)"),
    "ctrl/fence": ("span", "serving/engine.py",
                   "recorder instant: a FENCE executed on a replica "
                   "frontend (attrs: cancelled queued/active counts)"),
    "ctrl/lease_resize": ("span", "serving/fleet/health.py",
                          "recorder instant: an adaptive lease resize on "
                          "the replica's lease track (attrs: direction, "
                          "scale, gap_ewma, loss)"),
    "ctrl/lifecycle": ("span", "serving/fleet/router.py",
                       "recorder instant: a lifecycle command was issued "
                       "(attrs: rid, op, seq, epoch)"),
    "ctrl/autoscale": ("track", "serving/fleet/autoscale.py",
                       "flight-recorder track of autoscaler decision "
                       "instants (ctrl/autoscale/<action>)"),
    "ctrl/overload": ("track", "serving/fleet/autoscale.py",
                      "flight-recorder track of brownout-rung occupancy "
                      "intervals (ctrl/overload/<rung>)"),
    # ---- control-plane transport health gauges (serving/fleet/router.py,
    #      exported once per fleet round; the per-rid link gauges are the
    #      DYNAMIC transport/ gauge family)
    "transport/retransmit_depth": ("gauge", "serving/fleet/router.py",
                                   "reliable-stream sends currently "
                                   "awaiting an ack (unacked fences + "
                                   "migration chunks + directory "
                                   "resyncs), sampled once per fleet "
                                   "round"),
    # ---- step anatomy (telemetry/step_anatomy.py, folded by
    #      serving/engine.py; docs/OBSERVABILITY.md "Step anatomy")
    "engine/recompiles": ("counter", "serving/engine.py",
                          "JIT cache misses folded from the step-anatomy "
                          "compile tracker (warm-up included)"),
    "engine/recompile_steady_state": ("event+counter", "serving/engine.py",
                                      "a step program compiled AFTER the "
                                      "warm-up boundary — the AOT "
                                      "serving-step regression guard"),
    "anatomy/step": ("span", "serving/engine.py",
                     "flight-recorder span: one engine step's anatomy "
                     "(attrs: shape, host/device/gap seconds, compiles) "
                     "on the anatomy/<frontend> track"),
    "anatomy/device": ("span", "telemetry/step_anatomy.py",
                       "device-compute child of an emit_spans "
                       "anatomy/step (host segments ride as "
                       "anatomy/<segment> via the DYNAMIC family)"),
    # ---- engine-step tracer spans (runtime/engine.py set_telemetry)
    "engine/step": ("span", "runtime/engine.py",
                    "one train_batch trace root on the engine track"),
    "engine/fwd_bwd": ("span", "runtime/engine.py",
                       "forward+backward child of engine/step"),
    "engine/optim": ("span", "runtime/engine.py",
                     "optimizer child of engine/step (nvme/host tiers)"),
    "engine/fused_step": ("span", "runtime/engine.py",
                          "fused fwd+bwd+optim child of engine/step"),
    # ---- KV-arena occupancy (serving/engine.py export_kv_gauges; the
    #      per-rid / per-tenant variants are the DYNAMIC kv/ family)
    "kv/pages_in_use": ("gauge", "serving/engine.py",
                        "arena pages held by sequences and/or the prefix "
                        "cache"),
    "kv/pages_free": ("gauge", "serving/engine.py",
                      "arena pages on the free list"),
    "kv/page_occupancy": ("gauge", "serving/engine.py",
                          "in-use fraction of the usable arena"),
    "kv/free_run_fragmentation": ("gauge", "serving/engine.py",
                                  "1 - longest contiguous free page-id "
                                  "run / free pages (allocation churn)"),
    "kv/prefix_cache_pages": ("gauge", "serving/engine.py",
                              "pages pinned by prefix-cache entries"),
    "kv/prefix_cache_share": ("gauge", "serving/engine.py",
                              "prefix-cache share of in-use pages"),
    # ---- tiered KV (serving/kvtier — docs/SERVING.md "Tiered KV")
    "kv/demote": ("counter", "serving/kvtier/tier.py",
                  "sequence or prefix page staged d2h into the host tier"),
    "kv/promote": ("counter", "serving/kvtier/tier.py",
                   "host-tier pages promoted h2d (resume claim or "
                   "prefix-chain promote)"),
    "kv/park": ("event+counter", "serving/engine.py",
                "idle session demoted + parked (DECODE -> PARKED, zero "
                "device pages held)"),
    "kv/resume": ("event+counter", "serving/engine.py",
                  "parked session re-enqueued (PARKED -> QUEUED, promote "
                  "prefetch issued)"),
    "kv/watermark_demote": ("counter", "serving/kvtier/tier.py",
                            "pages moved by watermark enforcement (device "
                            "high-water prefix demotion + host LRU drops)"),
    "kv/host_pages": ("gauge", "serving/engine.py",
                      "host-tier pages held (demoted sequences + "
                      "warm-on-host prefix pages)"),
    "kv/tier_prefetch_hidden_frac": ("gauge", "serving/engine.py",
                                     "fraction of promote transfer "
                                     "seconds hidden under prior device "
                                     "windows by issued-ahead prefetch"),
    # ---- arrival-rate telemetry (serving/fleet/router.py, exported once
    #      per fleet round — ROADMAP's predictive-scale-up input)
    "fleet/arrival_rate_ewma": ("gauge", "serving/fleet/router.py",
                                "EWMA (alpha=0.2) of fleet request "
                                "arrivals per clock second"),
    "fleet/arrival_rate_slope": ("gauge", "serving/fleet/router.py",
                                 "per-round derivative of the arrival "
                                 "EWMA (scale BEFORE the queue grows)"),
    # ---- monitor surface (monitor/monitor.py)
    "monitor/dropped_events": ("event", "monitor/monitor.py",
                               "cumulative events shed by the max_events cap"),
    # ---- flops profiler gauges (profiling/flops_profiler/profiler.py)
    "profiler/flops_per_step": ("gauge", "profiling/flops_profiler/profiler.py",
                                "model FLOPs of the profiled step"),
    "profiler/macs_per_step": ("gauge", "profiling/flops_profiler/profiler.py",
                               "model MACs of the profiled step"),
    "profiler/params": ("gauge", "profiling/flops_profiler/profiler.py",
                        "parameter count"),
    "profiler/bytes_per_step": ("gauge", "profiling/flops_profiler/profiler.py",
                                "activation+weight bytes moved per step"),
    "profiler/step_duration_s": ("gauge", "profiling/flops_profiler/profiler.py",
                                 "measured wall duration of the profiled step"),
}

#: dynamic name families built with f-strings; ``prefix`` legitimizes the
#: emitter's literal head, ``expansions`` documents the closed value set
#: ("..." marks an open family)
DYNAMIC = [
    {"prefix": "serving/", "template": "serving/<terminal-state>",
     "kind": "counter", "source": "serving/engine.py",
     "expansions": ["serving/done", "serving/timed_out", "serving/migrated"],
     "doc": "terminal-state counter per finished request"},
    {"prefix": "fleet/", "template": "fleet/<terminal-state>",
     "kind": "event", "source": "serving/fleet/router.py",
     "expansions": ["fleet/done", "fleet/timed_out", "fleet/rejected"],
     "doc": "terminal-state event per finished fleet request"},
    {"prefix": "fleet/replica_", "template": "fleet/replica_<stat>/<rid>",
     "kind": "gauge", "source": "serving/fleet/router.py",
     "expansions": ["fleet/replica_queue_depth/<rid>",
                    "fleet/replica_free_kv_pages/<rid>",
                    "fleet/replica_outstanding_tokens/<rid>",
                    "fleet/replica_active/<rid>"],
     "doc": "per-replica load_stats snapshot exported once per fleet round"},
    {"prefix": "fleet/health/", "template": "fleet/health/<state>",
     "kind": "event", "source": "serving/fleet/health.py",
     "expansions": ["fleet/health/healthy", "fleet/health/degraded",
                    "fleet/health/draining", "fleet/health/dead",
                    "fleet/health/recovering"],
     "doc": "replica health transition (value = rid)"},
    {"prefix": "transport/", "template": "transport/<counter>",
     "kind": "counter", "source": "serving/fleet/transport.py",
     "expansions": ["transport/sent", "transport/delivered",
                    "transport/dropped", "transport/partition_dropped",
                    "transport/duplicated", "transport/reordered",
                    "transport/delayed", "transport/send_faults",
                    "transport/deliver_faults", "transport/retransmits"],
     "doc": "control-plane fabric accounting, one counter per fate a "
            "message can meet (docs/SERVING.md 'Control-plane transport')"},
    {"prefix": "telemetry/", "template": "telemetry/<metric>[_p50|_p95|_p99|_count]",
     "kind": "event", "source": "telemetry/metrics.py",
     "expansions": ["..."],
     "doc": "MetricsRegistry.flush_to_monitor bridge of every registered "
            "metric (histograms fan out quantiles + count)"},
    {"prefix": "ctrl/", "template": "ctrl/<name>",
     "kind": "span", "source": "serving/fleet/transport.py (+health.py, "
     "autoscale.py, telemetry/slo.py)",
     "expansions": ["ctrl/<message-kind> (send->deliver causal span, per "
                    "ctrl/link/<src>-<dst> track)",
                    "ctrl/lease/<state> (lease-lifecycle interval per "
                    "ctrl/lease/replica/<rid> track)",
                    "ctrl/overload/<rung>", "ctrl/autoscale/<action>",
                    "ctrl/slo/<tenant> (alert-window interval track)"],
     "doc": "flight-recorder control-plane span names: causal transport "
            "message pairs, lease/rung/alert intervals, autoscaler "
            "instants (docs/OBSERVABILITY.md 'Flight recorder')"},
    {"prefix": "slo/", "template": "slo/<signal>/<tenant>",
     "kind": "event+gauge", "source": "telemetry/slo.py",
     "expansions": ["slo/alert_fired/<tenant>", "slo/alert_cleared/<tenant>",
                    "slo/burn_fast/<tenant>", "slo/burn_slow/<tenant>"],
     "doc": "multi-window SLO burn-rate monitoring over per-tenant "
            "TenantSpec.ttft_slo: hysteresis-gated alert events + the "
            "fast/slow burn gauges, bit-reproducible under VirtualClock"},
    {"prefix": "transport/", "template": "transport/<link-gauge>/<rid>",
     "kind": "gauge", "source": "serving/fleet/router.py",
     "expansions": ["transport/link_loss_ewma/<rid>",
                    "transport/feed_gap_age/<rid>"],
     "doc": "per-link control-plane health, sampled once per fleet round "
            "— the adaptive-lease-sizing input signal (ROADMAP)"},
    {"prefix": "kv/", "template": "kv/<stat>/<rid-or-tenant>",
     "kind": "gauge", "source": "serving/fleet/router.py",
     "expansions": ["kv/page_occupancy/<rid>",
                    "kv/free_run_fragmentation/<rid>",
                    "kv/prefix_cache_share/<rid>",
                    "kv/tenant_pages/<tenant>"],
     "doc": "per-replica KV-arena occupancy + per-tenant page tallies "
            "(tenant tallies sum to the fleet's pages in use — the "
            "per-tenant KV-quota input), exported once per fleet round"},
    {"prefix": "anatomy/", "template": "anatomy/<name>",
     "kind": "gauge+span+track", "source": "serving/fleet/router.py "
     "(+serving/engine.py, telemetry/step_anatomy.py)",
     "expansions": ["anatomy/host_gap_fraction/<rid> (gauge)",
                    "anatomy/<frontend> (flight-recorder track of "
                    "anatomy/step spans, e.g. anatomy/replica0)"],
     "doc": "step-anatomy surfaces: per-replica host-gap-fraction gauges "
            "once per fleet round + per-step recorder tracks "
            "(docs/OBSERVABILITY.md 'Step anatomy')"},
]

BEGIN_MARK = ("<!-- BEGIN EVENT TABLE (generated from "
              "deepspeed_tpu/telemetry/event_registry.py — edit there, then "
              "`python deepspeed_tpu/telemetry/event_registry.py --sync "
              "docs/OBSERVABILITY.md`) -->")
END_MARK = "<!-- END EVENT TABLE -->"


def registered_names():
    return frozenset(EVENTS)


def dynamic_prefixes():
    return tuple(d["prefix"] for d in DYNAMIC)


def _cell(text: str) -> str:
    # GFM splits table cells on '|' even inside code spans
    return text.replace("|", "\\|")


def render_event_table() -> str:
    """The markdown block committed between the OBSERVABILITY.md markers.
    Deterministic: sorted rows, no timestamps."""
    lines = [BEGIN_MARK, "",
             "| event | kind | emitted by | meaning |",
             "|---|---|---|---|"]
    for name in sorted(EVENTS):
        kind, source, doc = EVENTS[name]
        lines.append(f"| `{_cell(name)}` | {_cell(kind)} | `{_cell(source)}` "
                     f"| {_cell(doc)} |")
    for d in sorted(DYNAMIC, key=lambda d: d["template"]):
        exp = ", ".join(f"`{_cell(e)}`" for e in d["expansions"])
        lines.append(f"| `{_cell(d['template'])}` | {_cell(d['kind'])} | "
                     f"`{_cell(d['source'])}` | "
                     f"{_cell(d['doc'])} — expands to: {exp} |")
    lines += ["", END_MARK]
    return "\n".join(lines)


def extract_doc_block(doc_text: str):
    """The committed table block (markers included), or None."""
    m = re.search(re.escape(BEGIN_MARK) + r".*?" + re.escape(END_MARK),
                  doc_text, re.DOTALL)
    return m.group(0) if m else None


def sync_doc(doc_path: str) -> bool:
    """Rewrite the generated block in ``doc_path``; returns True when the
    file changed.  The block must already exist (markers committed)."""
    with open(doc_path, "r", encoding="utf-8") as f:
        text = f.read()
    old = extract_doc_block(text)
    if old is None:
        raise SystemExit(f"{doc_path}: event-table markers not found — add\n"
                         f"{BEGIN_MARK}\n{END_MARK}")
    new = render_event_table()
    if old == new:
        return False
    with open(doc_path, "w", encoding="utf-8") as f:  # atomic-ok: doc regeneration, not a durability artifact
        f.write(text.replace(old, new))
    return True


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sync", metavar="DOC",
                    help="rewrite the generated event table in DOC")
    args = ap.parse_args()
    if args.sync:
        changed = sync_doc(args.sync)
        print(f"{args.sync}: {'updated' if changed else 'already in sync'}")
    else:
        print(render_event_table())
