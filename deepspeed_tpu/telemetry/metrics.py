"""Always-on metrics: counters, gauges, and fixed-log-bucket histograms.

The serving layer already computes exact percentiles in
``serving/metrics.py`` — by RETAINING every finished request, which is
the right call for a bench run and the wrong one for a long-lived
server.  This registry is the cheap always-on complement: a histogram is
a fixed array of log-spaced bucket counts (O(1) record, O(buckets)
memory forever), and p50/p95/p99 are read from the bucket boundaries
with geometric interpolation — bounded relative error (one bucket's
growth factor), zero sample retention.

Bridged into the existing monitor surface by :meth:`MetricsRegistry.
flush_to_monitor`: every metric becomes a ``telemetry/<name>`` event
tuple through ``MonitorMaster.write_events`` — same backends, same
``max_events`` cap, same ``dropped_events`` accounting as the rest of
the stack.
"""

import bisect
import math
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "HistogramWindow",
           "MetricsRegistry"]


class Counter:
    """Monotonic count (requests served, tokens generated, spans dropped)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: inc({n}) — counters only go up")
        self.value += n


class Gauge:
    """Last-write-wins value (queue depth, flops/step, free KV pages)."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed log-bucket histogram: p50/p95/p99 without sample retention.

    Buckets are ``lo * growth**k`` for k in [0, n); a sample lands in the
    bucket whose upper bound first reaches it.  Two overflow cells catch
    samples below ``lo`` (index 0 territory is [0, lo]) and above the top
    bound.  Negative samples are clamped to 0 and counted in
    ``clamped_negative`` — latencies cannot be negative; a negative
    sample is a clock bug upstream and hiding it entirely would mask
    that, while crashing the metrics path would take serving down with
    it.

    Default geometry: lo=1e-6, growth=2**0.5, n=64 spans 1µs..~4.3e3s
    with ≤ ~19% relative quantile error (half-octave buckets) in 64
    ints — always-on cheap.
    """

    __slots__ = ("name", "bounds", "counts", "count", "total", "min", "max",
                 "clamped_negative")

    def __init__(self, name: str, lo: float = 1e-6, growth: float = 2 ** 0.5,
                 n_buckets: int = 64):
        if not (lo > 0 and growth > 1 and n_buckets >= 2):
            raise ValueError(f"histogram {name}: need lo>0, growth>1, n_buckets>=2 "
                             f"(got lo={lo}, growth={growth}, n={n_buckets})")
        self.name = name
        self.bounds: List[float] = [lo * growth ** k for k in range(n_buckets)]
        self.counts: List[int] = [0] * (n_buckets + 1)  # +1 overflow cell
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.clamped_negative = 0

    def record(self, x: float) -> None:
        if x != x:  # NaN: refuse loudly — a NaN latency is a real bug
            raise ValueError(f"histogram {self.name}: NaN sample")
        if x < 0:
            self.clamped_negative += 1
            x = 0.0
        i = bisect.bisect_left(self.bounds, x)
        self.counts[i] += 1
        self.count += 1
        self.total += x
        self.min = x if self.min is None else min(self.min, x)
        self.max = x if self.max is None else max(self.max, x)

    def quantile(self, q: float) -> Optional[float]:
        """Value at quantile ``q`` in [0, 1], from bucket boundaries with
        geometric interpolation inside the landing bucket; clamped to the
        observed min/max so tail quantiles never exceed reality."""
        return _bucket_quantile(self.bounds, self.counts, self.count,
                                self.min, self.max, q)

    def summary(self) -> dict:
        return {
            "count": self.count,
            "sum": round(self.total, 9),
            "mean": round(self.total / self.count, 9) if self.count else None,
            "min": self.min, "max": self.max,
            "p50": self.quantile(0.50),
            "p95": self.quantile(0.95),
            "p99": self.quantile(0.99),
        }

    # ------------------------------------------------------------- windows

    def window(self) -> "HistogramWindow":
        """Snapshot this histogram's cumulative state for later windowed
        reads: ``hist.since(win)`` summarizes only the samples recorded
        AFTER the snapshot — the burn-rate primitive (telemetry/slo.py,
        docs/OBSERVABILITY.md "Burn-rate windows") without any sample
        retention.  The snapshot is O(buckets) at *snapshot* time; the
        ``record()`` hot path is untouched (the no-allocation disabled
        path stays pinned by the existing tracemalloc tests)."""
        return HistogramWindow(counts=tuple(self.counts), count=self.count,
                               total=self.total)

    def since(self, win: "HistogramWindow") -> dict:
        """Summary (count/sum/mean/p50/p95/p99) over the samples recorded
        since ``win`` was taken, by cumulative-count subtraction — the
        standard Prometheus-style windowed read of a cumulative histogram.
        Window quantiles interpolate within bucket bounds (the exact
        window min/max are unknowable without retention); the LIFETIME
        max bounds the overflow bucket, so a window whose samples exceed
        the top bound still reads a real tail instead of silently
        truncating at ``bounds[-1]``."""
        if len(win.counts) != len(self.counts):
            raise ValueError("window snapshot geometry mismatch")
        d_counts = [c - w for c, w in zip(self.counts, win.counts)]
        if any(d < 0 for d in d_counts):
            raise ValueError(f"histogram {self.name}: window snapshot is "
                             "newer than the histogram (counts went down)")
        d_count = self.count - win.count
        d_total = self.total - win.total
        return {
            "count": d_count,
            "sum": round(d_total, 9),
            "mean": round(d_total / d_count, 9) if d_count else None,
            "p50": _bucket_quantile(self.bounds, d_counts, d_count, None, self.max, 0.50),
            "p95": _bucket_quantile(self.bounds, d_counts, d_count, None, self.max, 0.95),
            "p99": _bucket_quantile(self.bounds, d_counts, d_count, None, self.max, 0.99),
        }


def _bucket_quantile(bounds: List[float], counts: List[int], count: int,
                     lo_clamp: Optional[float], hi_clamp: Optional[float],
                     q: float) -> Optional[float]:
    """Shared quantile core over a bucket-count vector (live histograms
    pass their cumulative counts + observed min/max clamps; windowed reads
    pass delta counts with only the lifetime max bounding the overflow
    bucket)."""
    if count == 0:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    rank = q * count
    cum = 0
    est = None
    for i, c in enumerate(counts):
        cum += c
        if cum >= rank and c > 0:
            if i == 0:
                lo, hi = 0.0, bounds[0]
            elif i >= len(bounds):
                lo = bounds[-1]
                hi = hi_clamp if hi_clamp is not None else bounds[-1]
            else:
                lo, hi = bounds[i - 1], bounds[i]
            # geometric midpoint-ish: interpolate by the rank's position
            # inside this bucket's count, in log space when possible
            frac = (rank - (cum - c)) / c
            if lo > 0 and hi > lo:
                est = lo * (hi / lo) ** frac
            else:
                est = lo + (hi - lo) * frac
            break
    if est is None:
        est = hi_clamp if hi_clamp is not None else bounds[-1]
    if lo_clamp is not None:
        est = max(lo_clamp, est)
    if hi_clamp is not None:
        est = min(hi_clamp, est)
    return est


class HistogramWindow:
    """Immutable cumulative-state snapshot of one :class:`Histogram` (see
    :meth:`Histogram.window`)."""

    __slots__ = ("counts", "count", "total")

    def __init__(self, counts: Tuple[int, ...], count: int, total: float):
        self.counts = counts
        self.count = count
        self.total = total


class MetricsRegistry:
    """Get-or-create registry; names are flat (``serving/ttft_s``).  A
    name registered as one kind cannot be re-registered as another."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, *args, **kw):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(name, *args, **kw)
        elif not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kw) -> Histogram:
        return self._get(name, Histogram, **kw)

    def names(self) -> List[str]:
        return sorted(self._metrics)

    def snapshot(self) -> dict:
        """Flat point-in-time dict: counters/gauges as scalars, histograms
        as their summary dicts.  Deterministic key order."""
        out = {}
        for name in self.names():
            m = self._metrics[name]
            out[name] = m.summary() if isinstance(m, Histogram) else m.value
        return out

    def window(self) -> dict:
        """Cumulative-state snapshot of every metric, for
        :meth:`snapshot_since` — counters snapshot their value, histograms
        their bucket state (:meth:`Histogram.window`); gauges are
        last-write-wins and carry no window state."""
        out = {}
        for name, m in self._metrics.items():
            if isinstance(m, Histogram):
                out[name] = m.window()
            elif isinstance(m, Counter):
                out[name] = m.value
        return out

    def snapshot_since(self, win: dict) -> dict:
        """Windowed read: counters as deltas since ``win``, histograms as
        windowed summaries (``Histogram.since``), gauges as their current
        value.  Metrics created after the snapshot window from zero.
        Deterministic key order, no sample retention anywhere — the
        burn-rate monitors' input shape."""
        out = {}
        for name in self.names():
            m = self._metrics[name]
            if isinstance(m, Histogram):
                prev = win.get(name)
                out[name] = m.since(prev) if prev is not None \
                    else m.since(HistogramWindow(
                        counts=(0,) * len(m.counts), count=0, total=0.0))
            elif isinstance(m, Counter):
                out[name] = m.value - win.get(name, 0.0)
            else:
                out[name] = m.value
        return out

    def flush_to_monitor(self, monitor, step: int = 0) -> int:
        """Bridge every metric into ``MonitorMaster.write_events`` as
        ``telemetry/<name>`` tuples (histograms fan out to ``_p50/_p95/
        _p99/_count``).  Returns how many events were offered; unset
        gauges and empty histograms are skipped."""
        if monitor is None or not getattr(monitor, "enabled", False):
            return 0
        events = []
        for name in self.names():
            m = self._metrics[name]
            if isinstance(m, Histogram):
                if m.count == 0:
                    continue
                s = m.summary()
                for k in ("p50", "p95", "p99"):
                    events.append((f"telemetry/{name}_{k}", float(s[k]), step))
                events.append((f"telemetry/{name}_count", float(m.count), step))
            elif m.value is not None:
                events.append((f"telemetry/{name}", float(m.value), step))
        if events:
            monitor.write_events(events)
        return len(events)
