"""Deterministic distributed tracing over the pluggable serving clock.

One trace follows one unit of work end-to-end — a training step through
its fwd/bwd/optim (and streamed-optimizer upload/compute/download)
phases, or a serving request from fleet submission through per-replica
attempts, preemptions and failover to its terminal state.  Spans form a
tree per ``trace_id``: each has a ``span_id``, optional ``parent_id``, a
``track`` (the Chrome-trace thread it renders on: ``router``,
``replica0`` ...), attributes, and point-in-time events.

Two properties distinguish this from a wall-clock tracer:

* **Pluggable clock** — timestamps come from whatever object exposes
  ``now()``: ``VirtualClock`` / ``ReplicaClockView`` (deterministic
  simulation time) or ``WallClock`` / the default perf-counter clock
  (real time).  A :class:`~..serving.fleet.sim.FleetSimulator` run on a
  seeded workload therefore produces a **bit-reproducible** trace — the
  exported Chrome JSON is byte-identical across runs and machines, which
  turns traces into regression artifacts instead of debugging ephemera.
* **Deterministic ids** — ``trace_id`` / ``span_id`` are per-tracer
  monotonic counters, not random 128-bit ids; same program order, same
  ids.

Overhead contract: the disabled path (:data:`NULL_TRACER`) allocates
NOTHING per call — every method returns the shared :data:`NULL_SPAN`
singleton, so instrumented hot loops (per-token delivery) cost one
attribute read + one predicate when tracing is off.  The test suite pins
this with tracemalloc.
"""

import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["Span", "SpanEvent", "Tracer", "NullTracer", "NULL_SPAN", "NULL_TRACER",
           "PerfClock"]


class PerfClock:
    """Default tracer clock: ``time.perf_counter`` zeroed at construction
    (matches WallClock's small-comparable-timestamps convention)."""

    def __init__(self):
        self._t0 = time.perf_counter()

    def now(self) -> float:
        return time.perf_counter() - self._t0


SpanEvent = Tuple[str, float, Optional[dict]]  # (name, ts, attrs)


class Span:
    """One timed operation.  Mutable until :meth:`Tracer.end`; ``end_ts``
    is None while open."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "track",
                 "start_ts", "end_ts", "attrs", "events")

    def __init__(self, name: str, trace_id: int, span_id: int,
                 parent_id: Optional[int], track: str, start_ts: float,
                 attrs: Optional[dict] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.track = track
        self.start_ts = start_ts
        self.end_ts: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[SpanEvent] = []

    # -- convenience mutators (no-ops on NULL_SPAN via subclass) ----------

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self

    def event(self, name: str, ts: float, attrs: Optional[dict] = None) -> "Span":
        self.events.append((name, ts, dict(attrs) if attrs else None))
        return self

    @property
    def duration(self) -> Optional[float]:
        return None if self.end_ts is None else self.end_ts - self.start_ts

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, id={self.span_id}, "
                f"parent={self.parent_id}, track={self.track!r}, "
                f"[{self.start_ts}, {self.end_ts}])")


class _NullSpan(Span):
    """Shared inert span: every mutator is a no-op returning self, so
    ``tracer.start_span(...).set(...).event(...)`` chains are safe (and
    allocation-free) when tracing is disabled."""

    def __init__(self):
        super().__init__("null", 0, 0, None, "null", 0.0)

    def set(self, **attrs) -> "Span":
        return self

    def event(self, name, ts, attrs=None) -> "Span":
        return self


NULL_SPAN = _NullSpan()


class _SpanCtx:
    """Context manager wrapper from :meth:`Tracer.span`."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> Span:
        return self.span

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.span.attrs.setdefault("error", f"{exc_type.__name__}: {exc}")
        self._tracer.end(self.span)


class Tracer:
    """Span collector with deterministic ids and a pluggable clock.

    ``clock``: any object with ``now() -> float`` (VirtualClock,
    WallClock, ReplicaClockView, :class:`PerfClock`).  ``max_spans``
    bounds retention: past it the OLDEST finished spans are dropped and
    counted in ``dropped_spans`` (a long-lived wall-clock server must not
    grow without bound; exporters report the loss instead of hiding it).
    """

    enabled = True

    def __init__(self, clock=None, max_spans: int = 100_000, recorder=None):
        self.clock = clock if clock is not None else PerfClock()
        self.max_spans = int(max_spans)
        #: optional retention sink (telemetry/flight_recorder.py): every
        #: FINISHED span is mirrored into the recorder's bounded per-track
        #: ring as it retains here, so crash-scoped dumps still hold the
        #: recent request phases after this tracer's own retention (or a
        #: clear()) let them go.  None = no mirroring (zero overhead).
        self.recorder = recorder
        # bounded deque: retention eviction is O(1) per span even once the
        # cap is reached (a list's del spans[:1] would memmove max_spans
        # entries per append on exactly the long-lived-server path the cap
        # exists for); finished spans, materialization order
        self.spans = deque(maxlen=self.max_spans if self.max_spans > 0 else None)
        self.dropped_spans = 0
        self._next_span = 1
        self._next_trace = 1

    # ------------------------------------------------------------- ids

    def new_trace_id(self) -> int:
        tid = self._next_trace
        self._next_trace += 1
        return tid

    def reserve_span_id(self) -> int:
        """Allocate a span id without materializing the span — callers
        that parent children before the parent's extent is known (a fleet
        attempt span, closed only when the attempt ends) reserve the id
        up front and materialize via :meth:`add_span` later."""
        sid = self._next_span
        self._next_span += 1
        return sid

    # ------------------------------------------------------------ spans

    def now(self) -> float:
        return self.clock.now()

    def start_span(self, name: str, trace_id: Optional[int] = None,
                   parent: Optional[Span] = None, parent_id: Optional[int] = None,
                   track: str = "main", start_ts: Optional[float] = None,
                   attrs: Optional[dict] = None) -> Span:
        if parent is not None and parent is not NULL_SPAN:
            trace_id = trace_id if trace_id is not None else parent.trace_id
            parent_id = parent_id if parent_id is not None else parent.span_id
        if trace_id is None:
            trace_id = self.new_trace_id()
        return Span(name, trace_id, self.reserve_span_id(), parent_id, track,
                    self.clock.now() if start_ts is None else start_ts, attrs)

    def end(self, span: Span, end_ts: Optional[float] = None) -> Span:
        if span is NULL_SPAN:
            return span
        span.end_ts = self.clock.now() if end_ts is None else end_ts
        if span.end_ts < span.start_ts:  # clock-domain mixups must not
            span.end_ts = span.start_ts  # produce negative durations
        self._retain(span)
        return span

    def span(self, name: str, **kw) -> _SpanCtx:
        """``with tracer.span("engine/step", track="engine") as s:`` —
        ends (and retains) the span on exit, tagging exceptions."""
        return _SpanCtx(self, self.start_span(name, **kw))

    def add_span(self, name: str, trace_id: int, start_ts: float, end_ts: float,
                 parent_id: Optional[int] = None, span_id: Optional[int] = None,
                 track: str = "main", attrs: Optional[dict] = None,
                 events: Optional[List[SpanEvent]] = None) -> Span:
        """Materialize a finished span retroactively (timestamps already
        known — e.g. phase spans derived from a request's state history at
        terminal time).  ``span_id`` accepts a previously reserved id."""
        span = Span(name, trace_id, span_id if span_id is not None
                    else self.reserve_span_id(), parent_id, track, start_ts, attrs)
        span.end_ts = max(end_ts, start_ts)
        if events:
            span.events.extend(events)
        self._retain(span)
        return span

    def _retain(self, span: Span) -> None:
        if self.spans.maxlen is not None and len(self.spans) == self.spans.maxlen:
            self.dropped_spans += 1  # the deque evicts the oldest span
        self.spans.append(span)
        if self.recorder is not None:
            self.recorder.observe(span)

    # ---------------------------------------------------------- queries

    def finished(self, trace_id: Optional[int] = None) -> List[Span]:
        if trace_id is None:
            return list(self.spans)
        return [s for s in self.spans if s.trace_id == trace_id]

    def clear(self) -> None:
        self.spans.clear()


class NullTracer:
    """Disabled tracer: every method returns a shared singleton and
    allocates nothing.  ``enabled`` is the one-predicate guard hot paths
    use to skip even building attribute dicts."""

    enabled = False
    spans: tuple = ()
    dropped_spans = 0
    recorder = None

    def new_trace_id(self) -> int:
        return 0

    def reserve_span_id(self) -> int:
        return 0

    def now(self) -> float:
        return 0.0

    def start_span(self, *a, **kw) -> Span:
        return NULL_SPAN

    def end(self, span, end_ts=None) -> Span:
        return NULL_SPAN

    def span(self, *a, **kw) -> "NullTracer":
        return self

    def add_span(self, *a, **kw) -> Span:
        return NULL_SPAN

    def finished(self, trace_id=None) -> tuple:
        return ()

    def clear(self) -> None:
        pass

    # context-manager protocol so ``with tracer.span(...)`` works disabled
    def __enter__(self) -> Span:
        return NULL_SPAN

    def __exit__(self, exc_type, exc, tb) -> None:
        return None


NULL_TRACER = NullTracer()
