"""Trace exporters: Chrome-trace/Perfetto JSON and JSONL, atomically written.

``to_chrome_trace`` renders finished spans as the Trace Event Format
(``ph:"X"`` complete events, µs timestamps) that chrome://tracing and
Perfetto load directly; span events become ``ph:"i"`` instants and each
track gets a ``thread_name`` metadata record.  Everything about the
output is deterministic: tracks are numbered in sorted-name order,
events are sorted by (track, ts, span_id), keys are sorted, and
timestamps are exact float µs of the clock readings — so a VirtualClock
trace serializes byte-identically across runs (the property the fleet
determinism test and the committed ``BENCH_ROUTER_TRACE.json`` artifact
pin).

Writers go through ``resilience.atomic_io`` — a trace artifact is a
bench receipt and must never be observable half-written.
"""

import json
from typing import Dict, Iterable, List, Optional

from ..resilience.atomic_io import atomic_write_bytes
from .trace import Span

__all__ = ["to_chrome_trace", "write_chrome_trace", "spans_to_jsonl",
           "write_jsonl", "load_chrome_trace"]

_US = 1e6  # clock seconds (or virtual steps) -> Chrome µs


def _clean(attrs: Optional[dict]) -> dict:
    """JSON-safe attribute dict (deterministic: sorted at dump time)."""
    if not attrs:
        return {}
    out = {}
    for k, v in attrs.items():
        if v is None or isinstance(v, (bool, int, float, str)):
            out[str(k)] = v
        elif isinstance(v, (list, tuple)):
            out[str(k)] = [x if isinstance(x, (bool, int, float, str)) else str(x)
                           for x in v]
        else:
            out[str(k)] = str(v)
    return out


def to_chrome_trace(spans: Iterable[Span], dropped_spans: int = 0,
                    meta: Optional[dict] = None) -> dict:
    """Render finished spans as a Chrome-trace document (dict)."""
    spans = [s for s in spans if s.end_ts is not None]
    tracks = sorted({s.track for s in spans})
    tids = {t: i for i, t in enumerate(tracks)}
    events: List[dict] = []
    for t in tracks:
        events.append({"ph": "M", "pid": 0, "tid": tids[t], "ts": 0,
                       "name": "thread_name", "args": {"name": t}})
    # deterministic render order; within a track, X events sorted by start
    # ts (then id) — the schema checker's per-track monotonicity invariant
    for s in sorted(spans, key=lambda s: (tids[s.track], s.start_ts, s.span_id)):
        args = _clean(s.attrs)
        args["trace_id"] = s.trace_id
        args["span_id"] = s.span_id
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        events.append({"ph": "X", "pid": 0, "tid": tids[s.track],
                       "ts": round(s.start_ts * _US, 3),
                       "dur": round((s.end_ts - s.start_ts) * _US, 3),
                       "name": s.name, "args": args})
        for ename, ets, eattrs in s.events:
            ea = _clean(eattrs)
            ea["trace_id"] = s.trace_id
            ea["span_id"] = s.span_id
            events.append({"ph": "i", "pid": 0, "tid": tids[s.track],
                           "ts": round(ets * _US, 3), "s": "t",
                           "name": ename, "args": ea})
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "format": "deepspeed_tpu.telemetry", "version": 1,
            "clock_unit_us": _US, "n_spans": len(spans),
            "dropped_spans": int(dropped_spans),
            "tracks": tracks,
        },
    }
    if meta:
        doc["otherData"].update(_clean(meta))
    return doc


def _dump(doc) -> bytes:
    return json.dumps(doc, sort_keys=True, separators=(",", ":")).encode("utf-8")


def write_chrome_trace(path: str, spans: Iterable[Span], dropped_spans: int = 0,
                       meta: Optional[dict] = None, site: Optional[str] = None) -> str:
    """Atomically write the Chrome-trace JSON; byte-identical for
    identical span streams."""
    return atomic_write_bytes(path, _dump(to_chrome_trace(
        spans, dropped_spans=dropped_spans, meta=meta)), site=site)


def span_to_record(s: Span) -> dict:
    return {
        "name": s.name, "trace_id": s.trace_id, "span_id": s.span_id,
        "parent_id": s.parent_id, "track": s.track,
        "start_ts": s.start_ts, "end_ts": s.end_ts,
        "attrs": _clean(s.attrs),
        "events": [{"name": n, "ts": t, "attrs": _clean(a)} for n, t, a in s.events],
    }


def spans_to_jsonl(spans: Iterable[Span]) -> str:
    """One JSON object per finished span, materialization order (the
    stream shape log pipelines ingest)."""
    lines = [json.dumps(span_to_record(s), sort_keys=True, separators=(",", ":"))
             for s in spans if s.end_ts is not None]
    return "\n".join(lines) + ("\n" if lines else "")


def write_jsonl(path: str, spans: Iterable[Span], site: Optional[str] = None) -> str:
    return atomic_write_bytes(path, spans_to_jsonl(spans).encode("utf-8"), site=site)


def load_chrome_trace(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
