"""Phase-span derivation: turn a request's state history into trace spans.

The serving layers already keep an exact, timestamped state history per
request (``ServingRequest.history`` / ``FleetRequest.history``) — the
tracer does not shadow it with live open/close bookkeeping on the hot
path.  Instead, when a request (or a failed-over replica attempt) ends,
its history is folded into contiguous **phase spans** here:

    queued    — QUEUED (admission queue, preemption requeue, backoff)
    prefill   — PREFILL (prompt + recompute-on-resume KV build)
    decode    — DECODE
    migrating — MIGRATING (paused for chunked KV export — the per-request
                migration cost of disaggregated serving)
    pending   — fleet-level router queue time (before dispatch, between
                failover displacement and re-dispatch)

Phase spans TILE the request's lifetime exactly — consecutive history
entries share boundary timestamps — which is the property
``scripts/trace_report.py`` verifies against the recorded TTFT/TPOT
accounting (sum of phases == ttft + tpot*(n-1) == e2e for completed
requests).  ``clamp_start`` exists for resumed fleet attempts: their
``ServingRequest.arrival_ts`` is backdated to the CLIENT arrival (so
replica-side aging/deadlines stay correct), but the attempt's spans must
start at its dispatch or they would double-count the previous attempt's
time."""

from typing import List, Optional, Tuple

from ..serving.request import RequestState, ServingRequest
from .trace import Span, Tracer

__all__ = ["PHASE_OF_STATE", "phase_intervals", "emit_attempt_spans"]

# RequestState -> phase name; EVICTED is transient (the requeue lands at
# the same timestamp) but named so a non-zero-length eviction window —
# e.g. a future async release — would still be visible, not silently
# merged into queue time.
PHASE_OF_STATE = {
    RequestState.QUEUED: "queued",
    RequestState.PREFILL: "prefill",
    RequestState.DECODE: "decode",
    RequestState.EVICTED: "evicted",
    # host-staging window of a KV migration (serving/kvtransfer): the
    # request is paused on the source replica while its pages export — the
    # per-request migration cost the disaggregation bench accounts for
    RequestState.MIGRATING: "migrating",
    # idle session with its KV demoted to the host tier (serving/kvtier):
    # zero device pages held; ends at resume() re-enqueue
    RequestState.PARKED: "parked",
}


def _carve_promote(intervals: List[Tuple[str, float, float]],
                   windows: List[Tuple[float, float]]
                   ) -> List[Tuple[str, float, float]]:
    """Carve h2d promotion transfer windows (``ServingRequest.
    promote_windows``) out of the ``parked``/``queued`` intervals they
    overlap, as ``promote`` pieces.  The pieces PARTITION each original
    interval (tiling preserved exactly): a resume's TTFT then splits into
    genuine queue wait vs promotion transfer instead of lumping both into
    ``queued``.  Windows never overlap other phases — the engine stalls
    admission until ``t_ready`` before stamping PREFILL."""
    if not windows:
        return intervals
    # merge overlapping/adjacent windows (seq + prefix promotes can abut)
    merged: List[List[float]] = []
    for w0, w1 in sorted(windows):
        if merged and w0 <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], w1)
        else:
            merged.append([w0, w1])
    out: List[Tuple[str, float, float]] = []
    for phase, t0, t1 in intervals:
        if phase not in ("parked", "tool_stall", "queued"):
            out.append((phase, t0, t1))
            continue
        cur = t0
        for w0, w1 in merged:
            lo, hi = max(cur, w0), min(t1, w1)
            if hi <= lo:
                continue
            if lo > cur:
                out.append((phase, cur, lo))
            out.append(("promote", lo, hi))
            cur = hi
        if t1 > cur:
            out.append((phase, cur, t1))
    return out


def phase_intervals(history: List[Tuple[RequestState, float]],
                    end_ts: Optional[float] = None,
                    clamp_start: Optional[float] = None,
                    tail_phase: Optional[str] = None,
                    park_phase: str = "parked"
                    ) -> List[Tuple[str, float, float]]:
    """Fold a state history into ``(phase, t0, t1)`` intervals.

    ``end_ts`` closes the last non-terminal state (required for displaced
    attempts whose history never reached a terminal entry); terminal
    entries are points and close the walk.  Zero-length intervals are
    dropped.  ``clamp_start`` clips every interval's start (see module
    docstring).

    ``tail_phase`` relabels the OPEN tail — the stretch from the last
    recorded transition to ``end_ts`` — with a caller-supplied phase
    name.  The fleet router uses ``"fenced"`` for lease-expired/fenced
    attempts: the router credits the phases it observed up to the last
    transition it could know about, and attributes the remainder of the
    attempt window — work served outside the replica's lease, later
    discarded by the fence — to ``phase/fenced``, so transport-mode
    traces still tile [arrival, terminal] exactly
    (scripts/trace_report.py).

    ``park_phase`` relabels PARKED intervals (``ServingRequest.
    park_phase``): ``"tool_stall"`` when a session parked the request
    mid-generation awaiting a tool result — same machinery, different
    attribution (a tool stall is the AGENT's latency, an idle park the
    user's think time)."""
    out: List[Tuple[str, float, float]] = []
    for i, (state, ts) in enumerate(history):
        if state.terminal:
            break
        open_tail = i + 1 >= len(history)
        if not open_tail:
            nxt = history[i + 1][1]
        elif end_ts is not None:
            nxt = end_ts
        else:
            break  # open-ended non-terminal tail with no close time: skip
        t0 = ts if clamp_start is None else max(ts, clamp_start)
        if nxt > t0 and state in PHASE_OF_STATE:
            if open_tail and tail_phase is not None:
                phase = tail_phase
            elif state is RequestState.PARKED:
                phase = park_phase
            else:
                phase = PHASE_OF_STATE[state]
            out.append((phase, t0, nxt))
    return out


def emit_attempt_spans(tracer: Tracer, req: ServingRequest, trace_id: int,
                       parent_id: Optional[int], track: str,
                       end_ts: Optional[float] = None,
                       clamp_start: Optional[float] = None,
                       tail_phase: Optional[str] = None) -> List[Span]:
    """Materialize one serving attempt's phase spans (children of
    ``parent_id``) plus its preemption span events.  Used by the serving
    frontend at request terminal and by the fleet router for the partial
    attempt a replica death (or lease expiry — ``tail_phase="fenced"``)
    displaced."""
    spans = []
    intervals = phase_intervals(req.history, end_ts=end_ts,
                                clamp_start=clamp_start,
                                tail_phase=tail_phase,
                                park_phase=getattr(req, "park_phase",
                                                   "parked"))
    intervals = _carve_promote(intervals,
                               getattr(req, "promote_windows", None) or [])
    for phase, t0, t1 in intervals:
        spans.append(tracer.add_span(f"phase/{phase}", trace_id, t0, t1,
                                     parent_id=parent_id, track=track))
    return spans
