"""Logging utilities.

TPU-native analog of the reference logger factory
(``deepspeed/utils/logging.py:22 LoggerFactory``, ``log_dist:86``).  In the
single-controller JAX model there is one Python process per host, so
"rank-filtered" logging filters on ``jax.process_index()`` instead of a
torch.distributed rank.
"""

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


class LoggerFactory:

    @staticmethod
    def create_logger(name=None, level=logging.INFO):
        if name is None:
            raise ValueError("name for logger cannot be None")
        formatter = logging.Formatter(
            "[%(asctime)s] [%(levelname)s] [%(filename)s:%(lineno)d:%(funcName)s] %(message)s")
        logger_ = logging.getLogger(name)
        logger_.setLevel(level)
        logger_.propagate = False
        if not logger_.handlers:
            ch = logging.StreamHandler(stream=sys.stdout)
            ch.setLevel(level)
            ch.setFormatter(formatter)
            logger_.addHandler(ch)
        return logger_


logger = LoggerFactory.create_logger(
    name="DeepSpeedTPU", level=LOG_LEVELS.get(os.environ.get("DS_TPU_LOG_LEVEL", "info"), logging.INFO))


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:  # jax.distributed not initialised, or no backend yet
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log on selected process indices only (ref: utils/logging.py:86 log_dist)."""
    my_rank = _process_index()
    if ranks is None or len(ranks) == 0 or my_rank in ranks or (-1 in ranks):
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message):
    if _process_index() == 0:
        print(message, flush=True)


@functools.lru_cache(None)
def warn_once(message):
    logger.warning(message)


def should_log_le(max_log_level_str):
    if not isinstance(max_log_level_str, str):
        raise ValueError("max_log_level_str must be a string")
    max_log_level_str = max_log_level_str.lower()
    if max_log_level_str not in LOG_LEVELS:
        raise ValueError(f"Invalid log level: {max_log_level_str}")
    return logger.getEffectiveLevel() <= LOG_LEVELS[max_log_level_str]
