"""Wall-clock timers and throughput accounting.

TPU-native analog of ``deepspeed/utils/timer.py`` (ref:
``timer.py:44 SynchronizedWallClockTimer``, ``timer.py:199 ThroughputTimer``).
Where the reference synchronises CUDA streams before reading the clock, we
block on JAX async dispatch with ``jax.block_until_ready`` /
``jax.effects_barrier`` — the analogous fence for XLA's async execution model.
"""

import time

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


def _device_sync():
    """Drain the async dispatch queue so wall-clock reads cover device work
    (the CUDA-event-sync analog).  A zero-size device computation is used as
    a fence: block_until_ready on it waits for all previously enqueued work
    on the default stream-equivalent."""
    try:
        import numpy as np
        import jax.numpy as jnp
        # value fetch of a freshly enqueued computation: device queues are
        # FIFO, so its completion implies all prior work completed; a plain
        # block_until_ready is not a reliable fence on tunneled platforms
        np.asarray(jnp.zeros(()) + 0)
    except Exception:
        pass


class SynchronizedWallClockTimer:
    """Named timer group; mirrors the reference API surface
    (start/stop/reset/log, elapsed, mean)."""

    class Timer:

        def __init__(self, name):
            self.name_ = name
            self.started_ = False
            self.start_time = time.time()
            self.elapsed_records = []

        def start(self):
            assert not self.started_, f"{self.name_} timer has already been started"
            _device_sync()
            self.start_time = time.time()
            self.started_ = True

        def stop(self, reset=False, record=True):
            assert self.started_, "timer is not started"
            _device_sync()
            elapsed = time.time() - self.start_time
            if record:
                self.elapsed_records.append(elapsed)
            self.started_ = False

        def _init_timer(self):
            self.elapsed_records = []

        def reset(self):
            self.started_ = False
            self.elapsed_records = []

        def elapsed(self, reset=True):
            """Total elapsed seconds recorded (optionally reset)."""
            total = sum(self.elapsed_records)
            if self.started_:
                total += time.time() - self.start_time
            if reset:
                self.elapsed_records = []
            return total

        def mean(self):
            if not self.elapsed_records:
                return 0.0
            return sum(self.elapsed_records) / len(self.elapsed_records)

    def __init__(self):
        self.timers = {}

    def get_timers(self):
        return self.timers

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = self.Timer(name)
        return self.timers[name]

    def has_timer(self, name):
        return name in self.timers

    @staticmethod
    def memory_usage():
        try:
            import jax
            stats = jax.local_devices()[0].memory_stats() or {}
            in_use = stats.get("bytes_in_use", 0)
            peak = stats.get("peak_bytes_in_use", 0)
            return f"mem in-use {in_use / 2**30:.2f} GB | peak {peak / 2**30:.2f} GB"
        except Exception:
            return "mem stats unavailable"

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name in self.timers:
                elapsed_time = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                string += " | {}: {:.2f}".format(name, elapsed_time)
        if memory_breakdown:
            string += " | " + self.memory_usage()
        log_dist(string, ranks=ranks or [0])


class NoopTimer:
    """Disabled-timer stand-in (``wall_clock_breakdown=false``)."""

    class Timer:

        def start(self):
            ...

        def reset(self):
            ...

        def stop(self, **kwargs):
            ...

        def elapsed(self, **kwargs):
            return 0.0

        def mean(self):
            return 0.0

    def __init__(self):
        self.timer = self.Timer()

    def __call__(self, name):
        return self.timer

    def get_timers(self):
        return {}

    def log(self, names, normalizer=1.0, reset=True, memory_breakdown=False, ranks=None):
        ...


class ThroughputTimer:
    """Tokens/samples-per-second accounting (ref: timer.py:199)."""

    def __init__(self, batch_size, start_step=2, steps_per_output=None, monitor_memory=False, logging_fn=None):
        self.start_time = 0
        self.end_time = 0
        self.started = False
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0
        self.step_elapsed_time = 0
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.logging = logging_fn or log_dist
        self.initialized = False
        self._wall_start = None

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def _init_timer(self):
        self.initialized = False

    def _will_report(self):
        # only sync the device around steps whose timing is actually
        # reported: a device sync through a tunneled/remote backend costs
        # ~100ms, so syncing EVERY step (as the reference's cuda-event timer
        # harmlessly does locally) would serialize training (measured 3x
        # slowdown on axon-tunneled v5e)
        return bool(self.steps_per_output) and \
            (self.global_step_count + 1) % self.steps_per_output == 0

    def start(self):
        self.started = True
        if self.global_step_count >= self.start_step:
            if self._wall_start is None:
                self._wall_start = time.time()  # long-run average anchor
            if self._will_report():
                _device_sync()
                self.start_time = time.time()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        if self.start_time > 0:
            # synced per-step timing for CurrSamplesPerSec of THIS step only;
            # the running average uses un-synced wall clock (async-dispatch
            # error amortizes to zero over the run)
            _device_sync()
            self.end_time = time.time()
            self.step_elapsed_time += self.end_time - self.start_time
            self.start_time = 0
        if self._wall_start is not None:
            self.total_elapsed_time = time.time() - self._wall_start
            if global_step:
                if report_speed and self.steps_per_output and self.global_step_count % self.steps_per_output == 0:
                    self.logging("epoch={}/micro_step={}/global_step={}, RunningAvgSamplesPerSec={:.3f}, "
                                 "CurrSamplesPerSec={:.3f}".format(self.epoch_count, self.micro_step_count,
                                                                   self.global_step_count, self.avg_samples_per_sec(),
                                                                   self.batch_size / self.step_elapsed_time))
                self.step_elapsed_time = 0

    def avg_samples_per_sec(self):
        if self.global_step_count > self.start_step and self.total_elapsed_time > 0:
            samples = self.batch_size * (self.global_step_count - self.start_step)
            return samples / self.total_elapsed_time
        return float("-inf")
