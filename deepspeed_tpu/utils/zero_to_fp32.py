"""Import-path parity: the reference ships this as deepspeed/utils/zero_to_fp32.py.

Implementation lives in deepspeed_tpu/checkpoint/zero_to_fp32.py.
"""

from ..checkpoint.zero_to_fp32 import (convert_zero_checkpoint_to_fp32_state_dict,
                                       get_fp32_state_dict_from_zero_checkpoint,
                                       load_state_dict_from_zero_checkpoint, main)

if __name__ == "__main__":
    main()
