"""Process-group lookups — axis-name façade over the global mesh.

ref: deepspeed/utils/groups.py (707 LoC of torch.distributed subgroup
bookkeeping).  Under GSPMD a "group" IS a mesh axis: these helpers return
the axis names and sizes that collectives and shardings use, preserving the
reference's query surface for migrated code.
"""

from ..comm.mesh import (DATA_AXIS, EXPERT_AXIS, PIPE_AXIS, SEQ_AXIS, TENSOR_AXIS, ZERO_AXES, axis_size)
from ..comm.mesh import get_global_mesh as _mesh


def _size(*axes):
    m = _mesh()
    return axis_size(m, *[a for a in axes if m.shape.get(a, 1) > 1]) if m is not None else 1


def get_data_parallel_group():
    """ref: groups._get_data_parallel_group — the combined ZeRO/data axes."""
    return ZERO_AXES


def get_data_parallel_world_size():
    return _size(*ZERO_AXES)


def get_model_parallel_group():
    """ref: groups._get_model_parallel_group."""
    return (TENSOR_AXIS, )


def get_model_parallel_world_size():
    return _size(TENSOR_AXIS)


def get_tensor_model_parallel_group():
    return (TENSOR_AXIS, )


def get_tensor_model_parallel_world_size():
    return _size(TENSOR_AXIS)


def get_expert_parallel_group(name=None):
    """ref: groups._get_expert_parallel_group."""
    return (EXPERT_AXIS, )


def get_expert_parallel_world_size(name=None):
    return _size(EXPERT_AXIS)


def get_expert_data_parallel_group(name=None):
    """Expert-data group: DP axes excluding the expert axis."""
    return tuple(a for a in ZERO_AXES if a != EXPERT_AXIS)


def get_sequence_parallel_group():
    """ref: groups._get_sequence_parallel_group."""
    return (SEQ_AXIS, )


def get_sequence_parallel_world_size():
    return _size(SEQ_AXIS)


def get_pipeline_parallel_group():
    return (PIPE_AXIS, )


def get_pipeline_parallel_world_size():
    return _size(PIPE_AXIS)
