"""Profiler range annotation (ref: deepspeed/utils/nvtx.py:12
instrument_w_nvtx + accelerator range_push/pop).

On TPU the analog of NVTX ranges is ``jax.named_scope`` (shows up in
xprof/perfetto traces) plus ``jax.profiler.TraceAnnotation`` for host-side
spans."""

import functools

import jax


def instrument_w_nvtx(func):
    """Decorate ``func`` so its execution appears as a named range in
    profiler traces (ref: nvtx.py instrument_w_nvtx)."""

    @functools.wraps(func)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(func.__qualname__):
            with jax.named_scope(func.__qualname__):
                return func(*args, **kwargs)

    return wrapped


def range_push(name: str):
    """ref: accelerator.range_push — host-side profiler range begin."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    _STACK.append(ann)


def range_pop():
    if _STACK:
        _STACK.pop().__exit__(None, None, None)


_STACK = []
