"""Utils (ref: deepspeed/utils/): logging, timers, groups, nvtx,
zero_to_fp32."""

from .logging import LoggerFactory, log_dist, logger
from .nvtx import instrument_w_nvtx
from .timer import SynchronizedWallClockTimer, ThroughputTimer
