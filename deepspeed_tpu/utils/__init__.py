"""Utils (ref: deepspeed/utils/): logging, timers, groups, nvtx,
zero_to_fp32, tensor_fragment."""

from .logging import LoggerFactory, log_dist, logger
from .nvtx import instrument_w_nvtx
from .tensor_fragment import (safe_get_full_fp32_param, safe_get_full_grad,  # noqa: F401
                              safe_get_full_optimizer_state, safe_get_local_fp32_param,
                              safe_get_local_grad, safe_get_local_optimizer_state,
                              safe_set_full_fp32_param, safe_set_full_optimizer_state)
from .timer import SynchronizedWallClockTimer, ThroughputTimer
