"""Debug-side access to partitioned params, grads and optimizer states.

Parity for the reference's tensor-fragment API
(ref: deepspeed/utils/tensor_fragment.py:132 ``safe_get_full_fp32_param``,
``:148 safe_set_full_fp32_param``, ``:199 safe_get_full_grad``, and the
``safe_{get,set}_{full,local}_optimizer_state`` family) — the supported way
to inspect or patch a model mid-training regardless of how ZeRO/TP scattered
it.  There, fragments live on ``param.ds_tensor``/``param._hp_mapping`` and
gathers walk process groups.  Here the TrainState is a sharded pytree, so:

  * **get full** — resolve the leaf by name-path and pull it to host;
    materializing a sharded ``jax.Array`` as numpy IS the all-gather
    (XLA assembles the addressable shards).
  * **set full** — ``jax.device_put`` the new value against the leaf's
    recorded ``NamedSharding`` (the resharding write-back), rebuilding the
    immutable TrainState around it.  In mixed precision both the fp32
    master AND the compute-dtype param copy are written, like the
    reference's hp→lp sync (tensor_fragment.py ``safe_set_full_fp32_param``
    updates hp and marks lp dirty).
  * **get full grad** — grads never outlive the fused step program (XLA
    consumed them in the optimizer fusion), so the accessor RECOMPUTES the
    grad of the engine's last batch on demand via the engine's own
    accumulation program, then unscales — same numbers the step saw, at the
    cost of one fwd+bwd, paid only when asked.
  * **local** variants — the fragment resident on the first addressable
    device (the "my rank's shard" analog in single-process SPMD).

Paths name pytree keys separated by ``/`` (or ``.``): e.g.
``model/layers/self_attn/q_proj/kernel``.  With scan-stacked layers the
leaf carries the leading L dim.  The top-level ``params`` collection key is
optional.
"""

from typing import Any, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .logging import logger

PathLike = Union[str, Sequence[str]]


def _split(path: PathLike) -> Tuple[str, ...]:
    if isinstance(path, str):
        return tuple(k for k in path.replace(".", "/").split("/") if k)
    return tuple(path)


def _resolve(tree, keys: Tuple[str, ...], what: str):
    """Walk dict keys; the top-level 'params' wrapper may be elided."""
    if isinstance(tree, dict) and "params" in tree and keys and keys[0] != "params":
        tree = tree["params"]
    node = tree
    for i, k in enumerate(keys):
        if not isinstance(node, dict) or k not in node:
            avail = sorted(node) if isinstance(node, dict) else type(node).__name__
            raise KeyError(f"{what}: no key {'/'.join(keys[:i + 1])!r} "
                           f"(available at that level: {avail})")
        node = node[k]
    return node


def _set_in(tree, keys: Tuple[str, ...], value):
    if isinstance(tree, dict) and "params" in tree and keys and keys[0] != "params":
        return {**tree, "params": _set_in(tree["params"], keys, value)}
    if not keys:
        return value
    k = keys[0]
    if not isinstance(tree, dict) or k not in tree:
        raise KeyError(f"no key {k!r} while writing")
    return {**tree, k: _set_in(tree[k], keys[1:], value)}


def _unbox(leaf):
    from flax import linen as nn
    return nn.meta.unbox(leaf)


def _master_tree(engine):
    """The fp32 source of truth: ``state.master`` in mixed precision,
    ``state.params`` when compute dtype is fp32 (master aliased)."""
    m = engine.state.master
    use_master = not (isinstance(m, tuple) and len(m) == 0)
    return (m if use_master else engine.state.params), use_master


# ------------------------------------------------------------------ params

def safe_get_full_fp32_param(engine, path: PathLike) -> np.ndarray:
    """Full (gathered) fp32 value of a param, whatever its ZeRO-3/TP
    sharding (ref: tensor_fragment.py:132)."""
    tree, _ = _master_tree(engine)
    leaf = _unbox(_resolve(tree, _split(path), "param"))
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_get_local_fp32_param(engine, path: PathLike) -> np.ndarray:
    """This worker's resident fragment (first addressable shard) of the
    fp32 param (ref: safe_get_local_fp32_param)."""
    tree, _ = _master_tree(engine)
    leaf = _unbox(_resolve(tree, _split(path), "param"))
    return np.asarray(leaf.addressable_shards[0].data, dtype=np.float32)


def safe_set_full_fp32_param(engine, path: PathLike, value) -> None:
    """Write a full fp32 value back, resharding to the leaf's recorded
    NamedSharding; in mixed precision the compute-dtype copy is updated too
    (ref: tensor_fragment.py:148 — hp write + lp sync)."""
    keys = _split(path)
    state = engine.state
    master_tree, use_master = _master_tree(engine)
    old = _unbox(_resolve(master_tree, keys, "param"))
    value = jnp.asarray(value, old.dtype)
    if value.shape != old.shape:
        raise ValueError(f"shape mismatch writing {'/'.join(keys)}: "
                         f"{value.shape} vs {old.shape}")
    sh_tree = engine.state_shardings.master if use_master else engine.state_shardings.params
    sharding = _resolve(sh_tree, keys, "param sharding")
    new_master_leaf = jax.device_put(value, sharding)
    if use_master:
        new_master = _set_in(state.master, keys, new_master_leaf)
        p_old = _unbox(_resolve(state.params, keys, "param"))
        p_sh = _resolve(engine.state_shardings.params, keys, "param sharding")
        new_p_leaf = jax.device_put(value.astype(p_old.dtype), p_sh)
        new_params = _set_in(state.params, keys, new_p_leaf)
        engine.state = state._replace(params=new_params, master=new_master)
    else:
        engine.state = state._replace(params=_set_in(state.params, keys, new_master_leaf))


# safe_set_local_fp32_param: a per-shard write would race the SPMD layout
# (every process here addresses all shards); patch the full value instead.


# ------------------------------------------------------------------- grads

def _recompute_grads(engine, batch):
    key = ("_tensor_fragment_grads", engine._batch_key(batch))
    cache = getattr(engine, "_tf_grad_cache", None)
    if cache is None:
        cache = engine._tf_grad_cache = {}
    if key not in cache:

        def grads_fn(state, b):
            grads, _ = engine._grads_for_batch(state, b)
            # _grads_for_batch returns loss-scaled SUMMED grads over gas —
            # unscale exactly as _apply_grads does (incl. predivide) so these
            # ARE the step's effective pre-clip grads
            inv = 1.0 / (state.scaler.cur_scale * engine.gas)
            pdf = getattr(engine._config, "gradient_predivide_factor", 1.0) or 1.0
            if pdf != 1.0:
                inv = inv / pdf
            return jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)

        # land the grads in the step's own layout (ZeRO grad partitioning)
        # so the local accessor returns a true fragment
        out_sh = getattr(engine, "_grad_shardings", None)
        cache[key] = jax.jit(grads_fn, out_shardings=out_sh)
    from ..comm import mesh as mesh_lib
    # the trace happens at the CALL (jit is lazy) — it must see the mesh so
    # self-sharding Pallas kernels shard_map-wrap themselves
    with mesh_lib.trace_mesh(engine.mesh):
        return cache[key](engine.state, batch)


def safe_get_full_grad(engine, path: PathLike, batch=None) -> np.ndarray:
    """Full fp32 grad of a param for ``batch`` (default: the engine's last
    trained batch), recomputed on demand (ref: tensor_fragment.py:199 — the
    reference returns the grad stashed by the last backward; a fused XLA
    step leaves no stash, so the accessor re-derives it)."""
    batch = batch if batch is not None else getattr(engine, "last_batch", None)
    if batch is None:
        raise RuntimeError("safe_get_full_grad: no batch — train a step first or pass batch=")
    grads = _recompute_grads(engine, batch)
    leaf = _resolve(grads, _split(path), "grad")
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_get_local_grad(engine, path: PathLike, batch=None) -> np.ndarray:
    """This worker's fragment of the (recomputed) grad."""
    batch = batch if batch is not None else getattr(engine, "last_batch", None)
    if batch is None:
        raise RuntimeError("safe_get_local_grad: no batch — train a step first or pass batch=")
    grads = _recompute_grads(engine, batch)
    leaf = _resolve(grads, _split(path), "grad")
    return np.asarray(leaf.addressable_shards[0].data, dtype=np.float32)


# --------------------------------------------------------- optimizer state

_STATE_ALIASES = {"exp_avg": ("exp_avg", "mu", "m"),
                  "exp_avg_sq": ("exp_avg_sq", "nu", "v"),
                  "momentum": ("momentum", "trace", "exp_avg")}


def _locate_moments(opt_state, state_name: str):
    """Find the (container, field) carrying the per-param moment tree named
    ``state_name`` anywhere in the optimizer-state structure (fused
    optimizers are NamedTuples; chained/wrapped ones nest them)."""
    names = _STATE_ALIASES.get(state_name, (state_name, ))

    def walk(node, rebuild):
        if hasattr(node, "_fields"):
            for cand in names:
                if cand in node._fields:
                    return node, cand, rebuild
            for f in node._fields:
                found = walk(getattr(node, f),
                             lambda v, n=node, f=f, rb=rebuild: rb(n._replace(**{f: v})))
                if found is not None:
                    return found
        elif isinstance(node, (tuple, list)):
            for i, child in enumerate(node):
                found = walk(child,
                             lambda v, n=node, i=i, rb=rebuild:
                             rb(type(n)(list(n[:i]) + [v] + list(n[i + 1:]))))
                if found is not None:
                    return found
        return None

    found = walk(opt_state, lambda v: v)
    if found is None:
        raise KeyError(f"optimizer state has no field {state_name!r} "
                       f"(structure: {jax.tree.structure(opt_state)})")
    return found


def safe_get_full_optimizer_state(engine, path: PathLike, state_name: str) -> np.ndarray:
    """Full (gathered) fp32 optimizer state of a param — e.g. ``exp_avg`` /
    ``exp_avg_sq`` (ref: safe_get_full_optimizer_state)."""
    container, field, _ = _locate_moments(engine.state.opt_state, state_name)
    leaf = _resolve(getattr(container, field), _split(path), f"optimizer state {state_name}")
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_get_local_optimizer_state(engine, path: PathLike, state_name: str) -> np.ndarray:
    """This worker's fragment of the optimizer state."""
    container, field, _ = _locate_moments(engine.state.opt_state, state_name)
    leaf = _resolve(getattr(container, field), _split(path), f"optimizer state {state_name}")
    return np.asarray(leaf.addressable_shards[0].data, dtype=np.float32)


def safe_set_full_optimizer_state(engine, path: PathLike, value, state_name: str) -> None:
    """Write a full optimizer-state value back with resharding
    (ref: safe_set_full_optimizer_state)."""
    keys = _split(path)
    container, field, rebuild = _locate_moments(engine.state.opt_state, state_name)
    moments = getattr(container, field)
    old = _resolve(moments, keys, f"optimizer state {state_name}")
    value = jnp.asarray(value, old.dtype)
    if value.shape != old.shape:
        raise ValueError(f"shape mismatch writing {state_name} {'/'.join(keys)}: "
                         f"{value.shape} vs {old.shape}")
    sharding = old.sharding if hasattr(old, "sharding") else None
    new_leaf = jax.device_put(value, sharding) if sharding is not None else value
    new_opt = rebuild(container._replace(**{field: _set_in(moments, keys, new_leaf)}))
    engine.state = engine.state._replace(opt_state=new_opt)
