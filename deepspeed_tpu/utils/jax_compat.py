"""Version compatibility shims for the installed jax.

The codebase targets the current jax API surface; older installs (e.g.
0.4.x) keep ``shard_map`` under ``jax.experimental``.  Importing this
module (done at ``deepspeed_tpu`` package init, before any submodule
touches jax) aliases the experimental symbol onto the top-level namespace
so both ``jax.shard_map(...)`` and ``from jax import shard_map`` work
everywhere, tests included.
"""

import jax

if not hasattr(jax, "shard_map"):
    try:
        import functools

        from jax.experimental.shard_map import shard_map as _shard_map

        @functools.wraps(_shard_map)
        def _shard_map_compat(*args, **kwargs):
            # current jax names the replication check ``check_vma``; the
            # experimental version called it ``check_rep``
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)

        jax.shard_map = _shard_map_compat
    except ImportError:  # pragma: no cover - nothing to shim against
        pass

if not hasattr(jax.lax, "axis_size"):
    def _axis_size(axis_name):
        # the classic idiom predating jax.lax.axis_size: a psum of a
        # constant 1 over the named axis
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size
