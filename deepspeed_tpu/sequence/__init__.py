from .cross_entropy import (vocab_sequence_parallel_cross_entropy, vocab_sequence_parallel_per_token_loss)
from .layer import DistributedAttention

__all__ = [
    "DistributedAttention",
    "vocab_sequence_parallel_cross_entropy",
    "vocab_sequence_parallel_per_token_loss",
]
