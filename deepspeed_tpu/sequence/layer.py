"""DeepSpeed-Ulysses sequence parallelism, TPU-native.

Reference: ``deepspeed/sequence/layer.py`` — ``DistributedAttention:311``
wraps any attention impl with a head-scatter/seq-gather all-to-all before it
(``_SeqAllToAll:257``, ``single_all_to_all:221``) and the reverse after.

TPU-native realisation: activations live sequence-sharded over the ``seq``
mesh axis.  Around the attention core we simply *change the sharding
constraint* from (seq→``seq`` axis, heads→``tensor``) to (seq replicated,
heads→(``seq``, ``tensor``)); GSPMD lowers that resharding to exactly the
all-to-all the reference hand-codes, scheduled on ICI.  Two code paths:

* ``DistributedAttention`` — GSPMD constraint-based (works under plain jit).
* ``ulysses_all_to_all`` / ``UlyssesAttentionShardMap`` — explicit
  ``jax.lax.all_to_all`` for use inside ``shard_map`` (parity with
  ``single_all_to_all``'s explicit scatter/gather semantics).
"""

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, get_global_mesh


def _mesh_has(axis):
    mesh = get_global_mesh()
    return mesh.shape.get(axis, 1) > 1


class DistributedAttention:
    """Wraps an attention impl with Ulysses seq↔head resharding
    (ref: sequence/layer.py:311 DistributedAttention).

    ``attn_fn(q, k, v, **kw)`` takes [B, S, H, D] tensors.  scatter_idx=2
    (heads), gather_idx=1 (sequence) mirror the reference's defaults.
    """

    def __init__(self, attn_fn: Callable, scatter_idx: int = 2, gather_idx: int = 1):
        self.attn_fn = attn_fn
        self.scatter_idx = scatter_idx
        self.gather_idx = gather_idx

    def __call__(self, q, k, v, **kwargs):
        if not _mesh_has(SEQ_AXIS):
            return self.attn_fn(q, k, v, **kwargs)
        from jax.sharding import NamedSharding
        mesh = get_global_mesh()
        # pre-attention: gather sequence, scatter heads over (seq, tensor)
        head_axes = (SEQ_AXIS, TENSOR_AXIS)
        inner = NamedSharding(mesh, P(BATCH_AXES, None, head_axes, None))
        q, k, v = (jax.lax.with_sharding_constraint(t, inner) for t in (q, k, v))
        out = self.attn_fn(q, k, v, **kwargs)
        # post-attention: scatter sequence back, heads back to tensor-only
        outer = NamedSharding(mesh, P(BATCH_AXES, SEQ_AXIS,
                                      TENSOR_AXIS if _mesh_has(TENSOR_AXIS) else None, None))
        return jax.lax.with_sharding_constraint(out, outer)


def ulysses_all_to_all(x, axis_name: str, scatter_idx: int, gather_idx: int):
    """Explicit all-to-all for shard_map bodies (ref: single_all_to_all:221).

    Scatters dim ``scatter_idx`` across the axis and gathers dim
    ``gather_idx`` — e.g. [B, s_local, H, D] → [B, S, H/sp, D].
    """
    return jax.lax.all_to_all(x, axis_name, split_axis=scatter_idx, concat_axis=gather_idx, tiled=True)


def ulysses_attention_shard_map(attn_fn: Callable, mesh=None, seq_axis: str = SEQ_AXIS):
    """Build a shard_map'd Ulysses attention: explicit collectives, for
    kernels (e.g. Pallas flash) that must see the full sequence locally.

    Uneven head counts (H % sp != 0, ref: deepspeed/sequence/layer.py:111)
    are handled by zero-padding the head dim to the next sp multiple before
    the head-scatter all-to-all and slicing it off after the seq-gather:
    padded heads attend zero k/v (output exactly zero) and never reach the
    caller.  The constraint-based ``DistributedAttention`` needs no padding
    — GSPMD shards non-divisible dims with implicit padding."""
    mesh = mesh or get_global_mesh()
    sp = mesh.shape.get(seq_axis, 1)
    tp = mesh.shape.get(TENSOR_AXIS, 1)
    qkv_spec = P(BATCH_AXES, seq_axis, TENSOR_AXIS if tp > 1 else None, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec)
    def wrapped(q, k, v):
        if sp > 1:
            q = ulysses_all_to_all(q, seq_axis, 2, 1)
            k = ulysses_all_to_all(k, seq_axis, 2, 1)
            v = ulysses_all_to_all(v, seq_axis, 2, 1)
        out = attn_fn(q, k, v, causal=True)
        if sp > 1:
            out = ulysses_all_to_all(out, seq_axis, 1, 2)
        return out

    def call(q, k, v):
        h = q.shape[2]
        # heads are first split over TENSOR by qkv_spec, and each TP shard's
        # local heads then scatter over the seq group — so the pad target is
        # a multiple of sp·tp, not just sp
        unit = sp * tp
        pad = (-h) % unit
        if pad or k.shape[2] % unit:
            # the head-scatter all_to_all needs BOTH head dims divisible by
            # sp; GQA kv heads that aren't (whether or not q needs padding)
            # are repeated to full width first so the group ratio survives
            if k.shape[2] != h:
                rep = h // k.shape[2]
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            if pad:
                q = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
                k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
                v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
        out = wrapped(q, k, v)
        return out[:, :, :h] if pad else out

    return call
