"""Sequence-parallel vocab cross entropy.

ref: deepspeed/sequence/cross_entropy.py:1 (_VocabSequenceParallelCrossEntropy
— per-rank nll over the local sequence shard, all-gathered across the SP
group) and megatron's vocab-parallel CE.

TPU-native shape: the loss is pure jnp with GSPMD doing the sharded math —
``vocab_sequence_parallel_cross_entropy`` constrains the logits to the
(data×expert, seq, tensor) layout (sequence sharded over the SP axis, vocab
over TP) and computes CE as ``logsumexp(logits) − logits[target]``.  The
reductions stream over the vocab axis, so no replicated [B, S, V] tensor —
nor even an f32 log-prob tensor of the sharded size — is ever materialized;
the per-token loss comes out [B, S] sharded (data, seq) and the mean is a
psum.  The backward (softmax − onehot) is likewise generated sharded.

At BASELINE config 4 (Llama-8B, 32k ctx, V=128256) the replicated f32 logits
alone are B·32768·128256·4 bytes ≈ 16.8 GB/sample — this layout divides that
by sp×tp.
"""

import jax
import jax.numpy as jnp

from ..models.llama import causal_lm_loss, logits_constraint


def vocab_sequence_parallel_cross_entropy(logits, target, loss_mask=None):
    """Token-mean CE over [B, S, V] logits sharded (batch=data, seq=sp,
    vocab=tp).  Drop-in for the reference's loss (which takes [S/P, B, V];
    here batch-major like the rest of the stack)."""
    logits = logits_constraint(logits)
    return causal_lm_loss(logits, target, loss_mask)


def vocab_sequence_parallel_per_token_loss(logits, target):
    """Per-token nll [B, S] (the reference returns the all-gathered [S, B]
    loss tensor; GSPMD keeps ours sharded until consumed)."""
    logits = logits_constraint(logits)
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    tgt = jnp.take_along_axis(logits, target[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return lse - tgt
