"""Ring attention — true context parallelism over the ``seq`` mesh axis.

The reference has no blockwise ring attention (SURVEY §2.3: long-context
there is Ulysses + FPDT chunking, ``deepspeed/sequence/fpdt_layer.py``).  On
TPU a ring schedule is the natural long-context design: KV blocks rotate
around the ICI ring via ``lax.ppermute`` while each device accumulates
attention for its resident Q block with an online-softmax merge — the same
math as FPDT's ``update_out_and_lse`` (ref: sequence/fpdt_layer.py:58) but
with the chunk stream coming from neighbours over ICI instead of from host
memory.  Sequence length per device stays constant as the ``seq`` axis grows,
so context scales linearly with chips.

Design notes:
  * SPMD via ``shard_map``; the per-step ``ppermute`` is independent of that
    step's block compute, so XLA's latency-hiding scheduler overlaps the
    collective-permute with the attention matmuls (the hand-rolled double
    buffering of the reference's FPDT falls out of program order).
  * Causal skip: a block whose source rank sits strictly after ours is fully
    masked; a per-device ``lax.cond`` skips its FLOPs entirely.  Rank r
    computes r+1 of the P blocks — the usual causal ring imbalance; the
    ``striped`` layout (each rank holds an interleaved stripe of the
    sequence, see ``striped_ring_attention``) rebalances it.
  * Gradients flow through ``lax.scan`` + ``ppermute`` transpose rules, so
    the backward pass is itself a ring program — no custom VJP needed.
"""

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, get_global_mesh

_NEG_INF = -1e30


def _match_vma(like):
    """Return a fn casting an unvarying array to the varying-manual-axes set
    of ``like`` (shard_map vma typing; no-op outside shard_map)."""
    axes = getattr(jax.typeof(like), "vma", None) if hasattr(jax, "typeof") else None
    if not axes:
        return lambda x: x
    return lambda x: jax.lax.pcast(x, tuple(axes), to="varying")


def _block_partials(q32, k_blk, v_blk, q_pos, k_pos, scale, causal):
    """One Q-block × KV-block attention with running-softmax partials.

    q32: [B, sq, H, D] fp32; k_blk/v_blk: [B, sk, Hkv, D].
    Returns (m, l, o): [B, H, sq], [B, H, sq], [B, H, sq, D].
    """
    nh = q32.shape[2]
    nkv = k_blk.shape[2]
    if nkv != nh:
        rep = nh // nkv
        k_blk = jnp.repeat(k_blk, rep, axis=2)
        v_blk = jnp.repeat(v_blk, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [sq, sk]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)  # [B, H, sq]
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32))
    return m, l, o


def _merge(m, l, acc, m_blk, l_blk, o_blk):
    """Online-softmax merge of a new block into the running accumulator
    (same recurrence as ref sequence/fpdt_layer.py:58 update_out_and_lse)."""
    m_new = jnp.maximum(m, m_blk)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.exp(m_blk - m_new)
    l_new = a1 * l + a2 * l_blk
    acc_new = acc * a1[..., None] + o_blk * a2[..., None]
    return m_new, l_new, acc_new


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          block_ids: Optional[jnp.ndarray] = None):
    """Ring attention on local shards [B, s_local, H(local), D].

    ``block_ids``: for the plain layout, rank r holds contiguous block r; the
    striped layout passes explicit per-rank block indices instead.
    """
    ring = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, sq, nh, hd = q.shape
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    my_block = me if block_ids is None else block_ids
    q_pos = my_block * sq + jnp.arange(sq)

    m0 = jnp.full((b, nh, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, nh, sq), jnp.float32)
    acc0 = jnp.zeros((b, nh, sq, hd), jnp.float32)
    # match the varying-manual-axes type of the computed branch so the causal
    # skip cond and the scan carry typecheck under shard_map's vma system
    m0, l0, acc0 = jax.tree.map(_match_vma(q), (m0, l0, acc0))
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, t):
        m, l, acc, k_blk, v_blk, src_block = carry
        k_pos = src_block * sq + jnp.arange(sq)

        def compute(args):
            m, l, acc = args
            m_b, l_b, o_b = _block_partials(q32, k_blk, v_blk, q_pos, k_pos, scale, causal)
            return _merge(m, l, acc, m_b, l_b, o_b)

        if causal:
            # Fully-masked block (source strictly after us): skip its FLOPs.
            visible = src_block <= my_block
            m, l, acc = jax.lax.cond(visible, compute, lambda args: args, (m, l, acc))
        else:
            m, l, acc = compute((m, l, acc))

        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        src_nxt = jax.lax.ppermute(src_block, axis_name, perm)
        return (m, l, acc, k_nxt, v_nxt, src_nxt), None

    (m, l, acc, _, _, _), _ = jax.lax.scan(step, (m0, l0, acc0, k, v, my_block),
                                           jnp.arange(ring))
    out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, sq, H, D]


def ring_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                   mesh=None, seq_axis: str = SEQ_AXIS):
    """Context-parallel attention on globally [B, S, H, D] arrays whose S dim
    is sharded over ``seq_axis``.  Falls back to the jnp reference when the
    mesh has no sequence axis (so it is safe as a default attention impl)."""
    mesh = mesh or get_global_mesh()
    if mesh.shape.get(seq_axis, 1) == 1:
        from ..models.llama import reference_attention
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if segment_ids is not None:
        raise NotImplementedError("ring attention does not support segment_ids yet")

    q_spec, kv_spec = _qkv_specs(mesh, q.shape, k.shape, seq_axis)

    @partial(jax.shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec)
    def mapped(q, k, v):
        return _ring_attention_local(q, k, v, axis_name=seq_axis, causal=causal)

    return mapped(q, k, v)


def _qkv_specs(mesh, q_shape, kv_shape, seq_axis: str):
    """[B, S, H, D] specs: batch over the data axes when divisible, sequence
    over the ring axis, heads over tensor ONLY when both the q and the kv head
    counts divide the tensor axis — otherwise heads stay replicated (sharding
    just one of them would break the GQA head↔group alignment per shard)."""
    import numpy as _np
    bsz_axes = [a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1]
    bspec = tuple(bsz_axes) if bsz_axes and q_shape[0] % int(
        _np.prod([mesh.shape[a] for a in bsz_axes])) == 0 else None
    tp_size = mesh.shape.get(TENSOR_AXIS, 1)
    hspec = (TENSOR_AXIS if tp_size > 1 and q_shape[2] % tp_size == 0
             and kv_shape[2] % tp_size == 0 else None)
    return (P(bspec, seq_axis, hspec, None), P(bspec, seq_axis, hspec, None))


def striped_ring_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                           mesh=None, seq_axis: str = SEQ_AXIS):
    """Load-balanced ("zigzag") causal ring attention.

    The plain causal ring gives rank r work proportional to r+1.  Here each
    rank holds TWO half-blocks — the r-th from the front of the sequence and
    the r-th from the back — so every rank sees the same masked/unmasked mix.
    The caller must lay out the sequence in zigzag order (see
    ``zigzag_reorder`` / ``zigzag_restore``); positions are reconstructed
    internally for the causal mask.
    """
    mesh = mesh or get_global_mesh()
    ring = mesh.shape.get(seq_axis, 1)
    if ring == 1:
        from ..models.llama import reference_attention
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if segment_ids is not None:
        raise NotImplementedError("striped ring attention does not support segment_ids")

    q_spec, kv_spec = _qkv_specs(mesh, q.shape, k.shape, seq_axis)

    @partial(jax.shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec)
    def mapped(q, k, v):
        me = jax.lax.axis_index(seq_axis)
        b, sl, nh, hd = q.shape
        half = sl // 2
        scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
        q32 = q.astype(jnp.float32)
        # local halves: front block index = me, back block index = 2*ring-1-me
        front, back = me, 2 * ring - 1 - me
        pos = jnp.concatenate([front * half + jnp.arange(half),
                               back * half + jnp.arange(half)])
        m0 = jnp.full((b, nh, sl), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((b, nh, sl), jnp.float32)
        acc0 = jnp.zeros((b, nh, sl, hd), jnp.float32)
        m0, l0, acc0 = jax.tree.map(_match_vma(q), (m0, l0, acc0))
        perm = [(j, (j + 1) % ring) for j in range(ring)]

        def step(carry, t):
            m, l, acc, k_blk, v_blk, src_front, src_back = carry
            k_pos = jnp.concatenate([src_front * half + jnp.arange(half),
                                     src_back * half + jnp.arange(half)])
            m_b, l_b, o_b = _block_partials(q32, k_blk, v_blk, pos, k_pos, scale, causal)
            m, l, acc = _merge(m, l, acc, m_b, l_b, o_b)
            k_nxt = jax.lax.ppermute(k_blk, seq_axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, seq_axis, perm)
            sf = jax.lax.ppermute(src_front, seq_axis, perm)
            sb = jax.lax.ppermute(src_back, seq_axis, perm)
            return (m, l, acc, k_nxt, v_nxt, sf, sb), None

        (m, l, acc, _, _, _, _), _ = jax.lax.scan(
            step, (m0, l0, acc0, k, v, front, back), jnp.arange(ring))
        out = acc / jnp.where(l == 0.0, 1.0, l)[..., None]
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)

    return mapped(q, k, v)


def zigzag_reorder(x, ring: int, axis: int = 1):
    """Permute a sequence dim into the zigzag layout consumed by
    ``striped_ring_attention``: rank r gets chunks (r, 2*ring-1-r)."""
    n = x.shape[axis]
    chunk = n // (2 * ring)
    idx = []
    for r in range(ring):
        idx.extend(range(r * chunk, (r + 1) * chunk))
        idx.extend(range((2 * ring - 1 - r) * chunk, (2 * ring - r) * chunk))
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_restore(x, ring: int, axis: int = 1):
    """Inverse of ``zigzag_reorder``."""
    n = x.shape[axis]
    chunk = n // (2 * ring)
    idx = []
    for r in range(ring):
        idx.extend(range(r * chunk, (r + 1) * chunk))
        idx.extend(range((2 * ring - 1 - r) * chunk, (2 * ring - r) * chunk))
    inv = [0] * n
    for new_pos, old_pos in enumerate(idx):
        inv[old_pos] = new_pos
    return jnp.take(x, jnp.asarray(inv), axis=axis)
