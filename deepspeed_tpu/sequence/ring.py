"""Ring attention — true context parallelism over the ``seq`` mesh axis.

The reference has no blockwise ring attention (SURVEY §2.3: long-context
there is Ulysses + FPDT chunking, ``deepspeed/sequence/fpdt_layer.py``).  On
TPU a ring schedule is the natural long-context design: KV blocks rotate
around the ICI ring via ``lax.ppermute`` while each device accumulates
attention for its resident Q block with an online-softmax merge — the same
math as FPDT's ``update_out_and_lse`` (ref: sequence/fpdt_layer.py:58) but
with the chunk stream coming from neighbours over ICI instead of from host
memory.  Sequence length per device stays constant as the ``seq`` axis grows,
so context scales linearly with chips.

Design notes:
  * SPMD via ``shard_map``; the per-step ``ppermute`` is independent of that
    step's block compute, so XLA's latency-hiding scheduler overlaps the
    collective-permute with the attention matmuls (the hand-rolled double
    buffering of the reference's FPDT falls out of program order).
  * Causal skip: a block whose source rank sits strictly after ours is fully
    masked; a per-device ``lax.cond`` skips its FLOPs entirely.  Rank r
    computes r+1 of the P blocks — the usual causal ring imbalance; the
    ``striped`` layout (each rank holds an interleaved stripe of the
    sequence, see ``striped_ring_attention``) rebalances it.
  * Gradients flow through ``lax.scan`` + ``ppermute`` transpose rules, so
    the backward pass is itself a ring program — no custom VJP needed.
"""

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, get_global_mesh

_NEG_INF = -1e30


def _match_vma(like):
    """Return a fn casting an unvarying array to the varying-manual-axes set
    of ``like`` (shard_map vma typing; no-op outside shard_map)."""
    axes = getattr(jax.typeof(like), "vma", None) if hasattr(jax, "typeof") else None
    if not axes:
        return lambda x: x
    return lambda x: jax.lax.pcast(x, tuple(axes), to="varying")


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool):
    """Ring attention on local shards [B, s_local, H(local), D].  Block
    partials and the online-softmax merge are shared with FPDT
    (fpdt_layer._chunk_partials / update_out_and_lse)."""
    from .fpdt_layer import _chunk_partials, update_out_and_lse
    ring = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, sq, nh, hd = q.shape
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    q32 = q.astype(jnp.float32)
    q_pos = me * sq + jnp.arange(sq)

    out0 = jnp.zeros((b, nh, sq, hd), jnp.float32)
    lse0 = jnp.full((b, nh, sq), _NEG_INF, jnp.float32)
    # match the varying-manual-axes type of the computed branch so the causal
    # skip cond and the scan carry typecheck under shard_map's vma system
    out0, lse0 = jax.tree.map(_match_vma(q), (out0, lse0))
    perm = [(j, (j + 1) % ring) for j in range(ring)]

    def step(carry, t):
        out, lse, k_blk, v_blk, src_block = carry
        k_pos = src_block * sq + jnp.arange(sq)

        def compute(args):
            out, lse = args
            b_out, b_lse = _chunk_partials(q32, k_blk, v_blk, q_pos, k_pos, scale, causal)
            return update_out_and_lse(out, lse, b_out, b_lse)

        if causal:
            # Fully-masked block (source strictly after us): skip its FLOPs.
            visible = src_block <= me
            out, lse = jax.lax.cond(visible, compute, lambda args: args, (out, lse))
        else:
            out, lse = compute((out, lse))

        k_nxt = jax.lax.ppermute(k_blk, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_blk, axis_name, perm)
        src_nxt = jax.lax.ppermute(src_block, axis_name, perm)
        return (out, lse, k_nxt, v_nxt, src_nxt), None

    (out, lse, _, _, _), _ = jax.lax.scan(step, (out0, lse0, k, v, me),
                                          jnp.arange(ring))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B, sq, H, D]


def ring_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                   mesh=None, seq_axis: str = SEQ_AXIS):
    """Context-parallel attention on globally [B, S, H, D] arrays whose S dim
    is sharded over ``seq_axis``.  Falls back to the jnp reference when the
    mesh has no sequence axis (so it is safe as a default attention impl)."""
    mesh = mesh or get_global_mesh()
    if mesh.shape.get(seq_axis, 1) == 1:
        from ..models.llama import reference_attention
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if segment_ids is not None:
        raise NotImplementedError("ring attention does not support segment_ids yet")

    q_spec, kv_spec = _qkv_specs(mesh, q.shape, k.shape, seq_axis)

    @partial(jax.shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec)
    def mapped(q, k, v):
        return _ring_attention_local(q, k, v, axis_name=seq_axis, causal=causal)

    return mapped(q, k, v)


def _qkv_specs(mesh, q_shape, kv_shape, seq_axis: str):
    """[B, S, H, D] specs: batch over the data axes when divisible, sequence
    over the ring axis, heads over tensor ONLY when both the q and the kv head
    counts divide the tensor axis — otherwise heads stay replicated (sharding
    just one of them would break the GQA head↔group alignment per shard)."""
    import numpy as _np
    bsz_axes = [a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1]
    bspec = tuple(bsz_axes) if bsz_axes and q_shape[0] % int(
        _np.prod([mesh.shape[a] for a in bsz_axes])) == 0 else None
    tp_size = mesh.shape.get(TENSOR_AXIS, 1)
    hspec = (TENSOR_AXIS if tp_size > 1 and q_shape[2] % tp_size == 0
             and kv_shape[2] % tp_size == 0 else None)
    return (P(bspec, seq_axis, hspec, None), P(bspec, seq_axis, hspec, None))


def striped_ring_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                           mesh=None, seq_axis: str = SEQ_AXIS):
    """Load-balanced ("zigzag") causal ring attention.

    The plain causal ring gives rank r work proportional to r+1.  Here each
    rank holds TWO half-blocks — the r-th from the front of the sequence and
    the r-th from the back — so every rank sees the same masked/unmasked mix.
    The caller must lay out the sequence in zigzag order (see
    ``zigzag_reorder`` / ``zigzag_restore``); positions are reconstructed
    internally for the causal mask.
    """
    mesh = mesh or get_global_mesh()
    ring = mesh.shape.get(seq_axis, 1)
    if ring == 1:
        from ..models.llama import reference_attention
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if segment_ids is not None:
        raise NotImplementedError("striped ring attention does not support segment_ids")

    q_spec, kv_spec = _qkv_specs(mesh, q.shape, k.shape, seq_axis)

    from .fpdt_layer import _chunk_partials, update_out_and_lse

    @partial(jax.shard_map, mesh=mesh, in_specs=(q_spec, kv_spec, kv_spec), out_specs=q_spec)
    def mapped(q, k, v):
        me = jax.lax.axis_index(seq_axis)
        b, sl, nh, hd = q.shape
        half = sl // 2
        scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
        q32 = q.astype(jnp.float32)
        # local halves: front block index = me, back block index = 2*ring-1-me
        front, back = me, 2 * ring - 1 - me
        pos = jnp.concatenate([front * half + jnp.arange(half),
                               back * half + jnp.arange(half)])
        out0 = jnp.zeros((b, nh, sl, hd), jnp.float32)
        lse0 = jnp.full((b, nh, sl), _NEG_INF, jnp.float32)
        out0, lse0 = jax.tree.map(_match_vma(q), (out0, lse0))
        perm = [(j, (j + 1) % ring) for j in range(ring)]

        def step(carry, t):
            out, lse, k_blk, v_blk, src_front, src_back = carry
            k_pos = jnp.concatenate([src_front * half + jnp.arange(half),
                                     src_back * half + jnp.arange(half)])
            b_out, b_lse = _chunk_partials(q32, k_blk, v_blk, pos, k_pos, scale, causal)
            out, lse = update_out_and_lse(out, lse, b_out, b_lse)
            k_nxt = jax.lax.ppermute(k_blk, seq_axis, perm)
            v_nxt = jax.lax.ppermute(v_blk, seq_axis, perm)
            sf = jax.lax.ppermute(src_front, seq_axis, perm)
            sb = jax.lax.ppermute(src_back, seq_axis, perm)
            return (out, lse, k_nxt, v_nxt, sf, sb), None

        (out, lse, _, _, _, _), _ = jax.lax.scan(
            step, (out0, lse0, k, v, front, back), jnp.arange(ring))
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)

    return mapped(q, k, v)


def zigzag_reorder(x, ring: int, axis: int = 1):
    """Permute a sequence dim into the zigzag layout consumed by
    ``striped_ring_attention``: rank r gets chunks (r, 2*ring-1-r)."""
    n = x.shape[axis]
    assert n % (2 * ring) == 0, f"seq len {n} not divisible by 2*ring={2*ring}"
    chunk = n // (2 * ring)
    idx = []
    for r in range(ring):
        idx.extend(range(r * chunk, (r + 1) * chunk))
        idx.extend(range((2 * ring - 1 - r) * chunk, (2 * ring - r) * chunk))
    return jnp.take(x, jnp.asarray(idx), axis=axis)


def zigzag_restore(x, ring: int, axis: int = 1):
    """Inverse of ``zigzag_reorder``."""
    n = x.shape[axis]
    assert n % (2 * ring) == 0, f"seq len {n} not divisible by 2*ring={2*ring}"
    chunk = n // (2 * ring)
    idx = []
    for r in range(ring):
        idx.extend(range(r * chunk, (r + 1) * chunk))
        idx.extend(range((2 * ring - 1 - r) * chunk, (2 * ring - r) * chunk))
    inv = [0] * n
    for new_pos, old_pos in enumerate(idx):
        inv[old_pos] = new_pos
    return jnp.take(x, jnp.asarray(inv), axis=axis)
