"""FPDT — Fully Pipelined Distributed Transformer (long-context attention).

Reference: ``deepspeed/sequence/fpdt_layer.py`` — chunks the local sequence,
streams KV chunks through device memory with host offload + double
buffering, and merges partial attention results with an online softmax
(``update_out_and_lse:58``; classes ``FPDT_Attention:971``,
``_FPDTGPUOffloadingAttentionImpl_:510``).

TPU-native realisation:

* ``chunked_attention`` — a ``lax.scan`` over KV chunks with the online-
  softmax recurrence.  Peak memory is O(S·chunk) instead of O(S²); XLA
  pipelines the chunk loads against the matmuls (the reference's hand-rolled
  double buffering is program order here).
* ``fpdt_attention`` — adds query chunking (outer scan), bounding live
  attention state to O(chunk²) per step: the full FPDT memory profile.
* Host offload: rather than manually shuttling KV chunks (the reference's
  ``FPDT_Offloading_Wrapper``), pair ``fpdt_attention`` with
  ``jax.checkpoint`` offload policies (``offload_dot_with_no_batch_dims`` /
  ``save_and_offload_only_these_names``) so XLA schedules HBM↔host DMAs —
  see ``runtime/activation_checkpointing``.
* Combined with Ulysses (``sequence/layer.py``) or ring attention
  (``sequence/ring.py``) for the distributed dimension: Ulysses/ring shard
  the sequence across chips; FPDT chunking bounds the per-chip working set.
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def update_out_and_lse(out, lse, block_out, block_lse):
    """Merge a new attention block into (out, lse) running state.

    Parity with ref ``sequence/fpdt_layer.py:58 update_out_and_lse``:
    out/block_out: [B, H, Sq, D] fp32; lse/block_lse: [B, H, Sq]
    (log-sum-exp including the running max).  Returns the merged pair.
    """
    lse_new = jnp.logaddexp(lse, block_lse)
    out_new = (out * jnp.exp(lse - lse_new)[..., None] +
               block_out * jnp.exp(block_lse - lse_new)[..., None])
    return out_new, lse_new


def _chunk_partials(q, k_chunk, v_chunk, q_pos, k_pos, scale, causal):
    """(out, lse) partials of one q-block × kv-chunk product.
    q: [B, Sq, H, D]; k/v_chunk: [B, C, Hkv, D] → out [B,H,Sq,D], lse [B,H,Sq].

    The matmuls keep their STORAGE dtype operands with f32 accumulation —
    bf16 inputs run the MXU at full rate; the r4 version upcast q AND k to
    f32 first, running both einsums at ~1/8 MXU throughput, which is most
    of why FPDT measured 3.95x slower than flash at 32k (BENCH_LONGCTX r4).
    The softmax bookkeeping (max/exp/log) stays f32."""
    nh, nkv = q.shape[2], k_chunk.shape[2]
    if nkv != nh:
        rep = nh // nkv
        k_chunk = jnp.repeat(k_chunk, rep, axis=2)
        v_chunk = jnp.repeat(v_chunk, rep, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_chunk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v_chunk.dtype), v_chunk,
                     preferred_element_type=jnp.float32)
    # normalise to a (out, lse) pair: out already implicitly scaled by exp(m)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    out = out / jnp.maximum(l, 1e-30)[..., None]
    return out, lse


def chunked_attention(q, k, v, *, chunk_size: int, causal: bool = True,
                      q_offset: int = 0, k_offset: int = 0):
    """Attention with the KV sequence streamed in chunks (inner FPDT loop).

    q: [B, Sq, H, D]; k/v: [B, Sk, Hkv, D]; Sk must divide by chunk_size.
    ``q_offset``/``k_offset`` are global position offsets (used by the outer
    query-chunk loop and by sequence-sharded callers).
    """
    b, sq, nh, hd = q.shape
    sk = k.shape[1]
    assert sk % chunk_size == 0, f"Sk={sk} not divisible by chunk_size={chunk_size}"
    n_chunks = sk // chunk_size
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    q_pos = q_offset + jnp.arange(sq)

    k_chunks = k.reshape(b, n_chunks, chunk_size, *k.shape[2:]).swapaxes(0, 1)
    v_chunks = v.reshape(b, n_chunks, chunk_size, *v.shape[2:]).swapaxes(0, 1)

    out0 = jnp.zeros((b, nh, sq, hd), jnp.float32)
    lse0 = jnp.full((b, nh, sq), _NEG_INF, jnp.float32)

    # per-chunk remat: without it the scan VJP stacks every chunk's
    # [B,H,Sq,chunk] score residuals — at S=32k that is the full S^2 score
    # matrix (24 GB measured), the exact thing FPDT exists to avoid.  The
    # backward recomputes one chunk's partials at a time instead.
    partials = jax.checkpoint(
        lambda q_, k_, v_, qp, kp: _chunk_partials(q_, k_, v_, qp, kp, scale, causal))

    def step(carry, inputs):
        out, lse = carry
        idx, k_c, v_c = inputs
        k_pos = k_offset + idx * chunk_size + jnp.arange(chunk_size)
        c_out, c_lse = partials(q, k_c, v_c, q_pos, k_pos)
        return update_out_and_lse(out, lse, c_out, c_lse), None
        # (a lax.cond skip of above-diagonal chunks was measured SLOWER on
        # v5e — 441 vs 334 ms at S=32k attention fwd+bwd, the branch breaks
        # the scan's software pipelining despite halving FLOPs; triangular
        # savings come from the STAGED flash path in fpdt_attention instead)

    (out, lse), _ = jax.lax.scan(step, (out0, lse0),
                                 (jnp.arange(n_chunks), k_chunks, v_chunks))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _flash_group_ok(q, k, sq, sk):
    """Staged-flash eligibility: the kernel path needs 128-aligned seq lens
    and a TPU-lowerable environment; GQA handled kernel-natively."""
    from ..ops.flash_attention import LANE
    return sq % LANE == 0 and sk % LANE == 0


def fpdt_attention(q, k, v, *, causal: bool = True, segment_ids=None,
                   query_chunk_size: int = 512, kv_chunk_size: int = 512,
                   q_offset: int = 0, k_offset: int = 0, use_flash: Optional[bool] = None,
                   flash_groups: int = 8):
    """Double-chunked attention: outer loop over query chunks, inner sweep
    over KV chunks (ref: FPDT_Attention:971 — both loops, minus the manual
    host staging which remat/offload policies supply declaratively).

    STAGED-FLASH path (r5, default on TPU when shapes allow): the query
    sequence splits into ``flash_groups`` groups and each group runs ONE
    triangular Pallas flash call against its visible kv PREFIX
    (``q_position_offset`` keeps causality exact in-kernel), wrapped in
    ``jax.checkpoint`` so only the group OUTPUTS survive to the backward —
    the FPDT memory profile at kernel-grade FLOPs.  The per-group prefix
    also realises the triangle structurally: total work is
    (G+1)/2G of the full square (a lax.cond skip inside the jnp scan was
    measured SLOWER — it breaks scan pipelining).  The jnp double-scan
    remains the fallback (CPU tests, ragged shapes, explicit
    use_flash=False)."""
    if segment_ids is not None:
        raise NotImplementedError("fpdt_attention does not support segment_ids yet")
    b, sq, nh, hd = q.shape
    sk = k.shape[1]
    eligible = (causal and q_offset == 0 and k_offset == 0 and sq == sk
                and _flash_group_ok(q, k, sq, sk))
    if use_flash and not eligible:
        # an explicit request must not silently drop offsets / assume sq==sk
        raise ValueError(
            "use_flash=True requires causal self-attention with q_offset=0, "
            f"k_offset=0, sq == sk and 128-aligned lengths (got causal={causal}, "
            f"q_offset={q_offset}, k_offset={k_offset}, sq={sq}, sk={sk})")
    if use_flash is None:
        use_flash = eligible
    if use_flash:
        from ..ops.flash_attention import flash_attention
        G = flash_groups
        while G > 1 and (sq % G or (sq // G) % 128):
            G //= 2
        glen = sq // G
        outs = []
        for g in range(G):
            q_grp = jax.lax.slice_in_dim(q, g * glen, (g + 1) * glen, axis=1)
            k_pfx = jax.lax.slice_in_dim(k, 0, (g + 1) * glen, axis=1)
            v_pfx = jax.lax.slice_in_dim(v, 0, (g + 1) * glen, axis=1)
            grp = jax.checkpoint(
                lambda q_, k_, v_, off=g * glen: flash_attention(
                    q_, k_, v_, causal=True, q_position_offset=off))
            outs.append(grp(q_grp, k_pfx, v_pfx))
        return jnp.concatenate(outs, axis=1)
    qc = min(query_chunk_size, sq)
    assert sq % qc == 0, f"Sq={sq} not divisible by query_chunk_size={qc}"
    n_q = sq // qc
    if n_q == 1:
        return chunked_attention(q, k, v, chunk_size=min(kv_chunk_size, k.shape[1]),
                                 causal=causal, q_offset=q_offset, k_offset=k_offset)

    q_chunks = q.reshape(b, n_q, qc, nh, hd).swapaxes(0, 1)

    def one_q_chunk(idx_and_chunk):
        idx, q_c = idx_and_chunk
        return chunked_attention(q_c, k, v, chunk_size=min(kv_chunk_size, k.shape[1]),
                                 causal=causal,
                                 q_offset=q_offset + idx * qc, k_offset=k_offset)

    # outer remat bounds the map VJP's saved state to the q-chunk OUTPUTS:
    # each q-chunk's inner KV scan is recomputed (and re-chunk-rematted)
    # during its own backward — O(chunk^2) live, the FPDT memory profile
    outs = jax.lax.map(jax.checkpoint(one_q_chunk), (jnp.arange(n_q), q_chunks))
    return outs.swapaxes(0, 1).reshape(b, sq, nh, hd)


def _current_sharding(ndim: int, memory_kind: str):
    """Batch-sharded NamedSharding on the global mesh (or single-device)
    with the given memory kind."""
    from ..comm.mesh import BATCH_AXES, get_global_mesh, has_global_mesh
    from jax.sharding import NamedSharding, PartitionSpec, SingleDeviceSharding
    if has_global_mesh():
        mesh = get_global_mesh()
        spec = PartitionSpec(*([BATCH_AXES] + [None] * (ndim - 1)))
        return NamedSharding(mesh, spec, memory_kind=memory_kind)
    return SingleDeviceSharding(jax.devices()[0], memory_kind=memory_kind)


def host_kv(k, v):
    """Place the full K/V on HOST memory (the FPDT offloading KV store,
    ref: sequence/fpdt_layer.py:510 _FPDTGPUOffloadingAttentionImpl_ — there
    a hand-managed pinned-host tensor pair; here a memory_kind placement).
    Feed the results to ``fpdt_host_offload_attention`` (jit the caller with
    matching pinned_host in_shardings to keep them host-resident)."""
    host = _current_sharding(k.ndim, "pinned_host")
    return jax.device_put(k, host), jax.device_put(v, host)


def fpdt_host_offload_attention(q, k, v, *, chunk_size: int = 512, causal: bool = True,
                                q_offset: int = 0, k_offset: int = 0):
    """Chunked attention whose KV lives in HOST memory: each iteration
    slices one chunk from the host-resident K/V and copies it into device
    memory before the matmuls (explicit ``jax.device_put`` inside the scan —
    XLA's latency-hiding scheduler overlaps chunk i+1's host→HBM copy with
    chunk i's compute, which is the reference's double buffering,
    ref: fpdt_layer.py:510).  Device-resident working set is O(chunk), not
    O(S); the [B, Sk, H, D] KV never materializes in HBM."""
    b, sq, nh, hd = q.shape
    sk = k.shape[1]
    assert sk % chunk_size == 0, f"Sk={sk} not divisible by chunk_size={chunk_size}"
    n_chunks = sk // chunk_size
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    q_pos = q_offset + jnp.arange(sq)
    dev = _current_sharding(k.ndim, "device")

    out0 = jnp.zeros((b, nh, sq, hd), jnp.float32)
    lse0 = jnp.full((b, nh, sq), _NEG_INF, jnp.float32)

    # per-chunk remat, same as chunked_attention: the scan VJP must not
    # stack every chunk's [B,H,Sq,chunk] score residuals (the full S^2
    # matrix at long context)
    partials = jax.checkpoint(
        lambda q_, k_, v_, qp, kp: _chunk_partials(q_, k_, v_, qp, kp, scale, causal))

    def step(carry, idx):
        out, lse = carry
        k_c = jax.lax.dynamic_slice_in_dim(k, idx * chunk_size, chunk_size, 1)
        v_c = jax.lax.dynamic_slice_in_dim(v, idx * chunk_size, chunk_size, 1)
        k_c = jax.device_put(k_c, dev)   # host → HBM, one chunk
        v_c = jax.device_put(v_c, dev)
        k_pos = k_offset + idx * chunk_size + jnp.arange(chunk_size)
        c_out, c_lse = partials(q, k_c, v_c, q_pos, k_pos)
        return update_out_and_lse(out, lse, c_out, c_lse), None

    (out, lse), _ = jax.lax.scan(step, (out0, lse0), jnp.arange(n_chunks))
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


class FPDTAttention:
    """Drop-in attention impl (``attn_fn(q, k, v, causal=..)``) combining
    FPDT chunking with optional Ulysses resharding when a ``seq`` mesh axis
    is live (ref class: sequence/fpdt_layer.py:971 FPDT_Attention)."""

    def __init__(self, query_chunk_size: int = 512, kv_chunk_size: int = 512,
                 ulysses: bool = True):
        self.query_chunk_size = query_chunk_size
        self.kv_chunk_size = kv_chunk_size
        self.ulysses = ulysses

    def __call__(self, q, k, v, *, causal: bool = True, segment_ids=None):
        inner = partial(fpdt_attention, causal=causal, segment_ids=segment_ids,
                        query_chunk_size=self.query_chunk_size,
                        kv_chunk_size=self.kv_chunk_size)
        if self.ulysses:
            from .layer import DistributedAttention
            return DistributedAttention(lambda q, k, v, **kw: inner(q, k, v))(q, k, v)
        return inner(q, k, v)
