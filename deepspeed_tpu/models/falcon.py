"""Falcon family — parallel attention/MLP decoder with MQA/GQA.

ref: deepspeed/inference/v2/model_implementations/falcon/ (+ the falcon
containers in module_inject).  Covers both layouts:
  * falcon-7b style: multi_query=True (1 KV head), parallel_attn=True,
    ONE input_layernorm shared by attention and MLP;
  * new_decoder_architecture (falcon-40b/180b): grouped KV heads with
    separate ln_attn / ln_mlp.

Blocks are parallel-residual: x + attn(ln(x)) + mlp(ln'(x)) — on TPU this
is a scheduling gift: the attention and MLP chains have no data dependency,
so XLA overlaps their matmuls (and their TP collectives) natively.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .llama import (EMBED, HEADS, HEAD_DIM, KV_HEADS, LAYERS, MLP, VOCAB, _logical, apply_rope,
                    get_attention_impl, rotary_embedding)


@dataclass(frozen=True)
class FalconConfig:
    vocab_size: int = 65024
    hidden_size: int = 4544
    num_hidden_layers: int = 32
    num_attention_heads: int = 71
    num_kv_heads: int = 1
    new_decoder_architecture: bool = False
    parallel_attn: bool = True
    num_ln_in_parallel_attn: int = 2  # new-arch: 2 = ln_attn+ln_mlp; 1 = shared (falcon-11B)
    ffn_hidden_size: int = 0  # 0 → 4*hidden_size (HF default); falcon2-style variants override
    alibi: bool = False  # falcon-rw: alibi position bias instead of rotary
    bias: bool = False
    layer_norm_epsilon: float = 1e-5
    rope_theta: float = 10000.0
    max_position_embeddings: int = 2048
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        new_arch = getattr(hf_cfg, "new_decoder_architecture", False)
        if new_arch:
            kv = getattr(hf_cfg, "num_kv_heads", hf_cfg.num_attention_heads)
        else:
            kv = 1 if getattr(hf_cfg, "multi_query", True) else hf_cfg.num_attention_heads
        fields = dict(vocab_size=hf_cfg.vocab_size,
                      hidden_size=hf_cfg.hidden_size,
                      num_hidden_layers=hf_cfg.num_hidden_layers,
                      num_attention_heads=hf_cfg.num_attention_heads,
                      num_kv_heads=kv,
                      new_decoder_architecture=new_arch,
                      # HF: None resolves to 2 only for the new decoder arch
                      num_ln_in_parallel_attn=(getattr(hf_cfg, "num_ln_in_parallel_attn", None)
                                               or (2 if new_arch else 1)),
                      parallel_attn=getattr(hf_cfg, "parallel_attn", True),
                      ffn_hidden_size=getattr(hf_cfg, "ffn_hidden_size", None) or 0,
                      alibi=getattr(hf_cfg, "alibi", False),
                      bias=getattr(hf_cfg, "bias", False),
                      layer_norm_epsilon=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
                      rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
                      tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", True))
        fields.update(overrides)
        return FalconConfig(**fields)


def alibi_slopes(n_heads: int) -> np.ndarray:
    """Standard alibi slope schedule (ref: HF build_alibi_tensor / the
    original train-short-test-long paper): powers of 2^(-8/m) for the
    closest power-of-two head count, interleaved extras otherwise."""
    import math

    def pow2_slopes(n):
        start = 2.0**(-(2.0**-(math.log2(n) - 3)))
        return [start**(i + 1) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return np.asarray(pow2_slopes(n_heads), np.float32)
    closest = 2**math.floor(math.log2(n_heads))
    extra = pow2_slopes(2 * closest)[0::2][:n_heads - closest]
    return np.asarray(pow2_slopes(closest) + extra, np.float32)


class FalconAttention(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        H, KV = cfg.num_attention_heads, cfg.num_kv_heads
        D = cfg.hidden_size // H
        dense = partial(nn.DenseGeneral, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        if cfg.alibi:
            # falcon-rw: alibi position bias instead of rotary — softmax is
            # row-shift-invariant, so slope*kpos ≡ slope*(kpos - qpos) under
            # the causal mask (ref: HF build_alibi_tensor)
            if cfg.attention_impl != "reference":
                raise NotImplementedError("alibi falcon requires attention_impl='reference'")
            slopes = jnp.asarray(alibi_slopes(H))                       # [H]
            kpos = positions.astype(jnp.float32)                        # [B, S]
            # HF adds alibi to the RAW scores before the 1/sqrt(D) scaling
            # ((QK + alibi) * inv_norm) — fold the scale into the bias since
            # reference_attention adds attn_bias post-scale
            bias = (slopes[None, :, None, None] * kpos[:, None, None, :]) / jnp.sqrt(jnp.float32(D))
            from .llama import reference_attention
            out = reference_attention(q, k, v, causal=True, segment_ids=segment_ids,
                                      attn_bias=bias)
        else:
            cos, sin = rotary_embedding(positions, D, cfg.rope_theta)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
            out = get_attention_impl(cfg.attention_impl)(q, k, v, causal=True, segment_ids=segment_ids)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=cfg.bias,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                               name="dense")(out)


class FalconBlock(nn.Module):
    cfg: FalconConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)
        def mlp(mlp_in):
            ffn = cfg.ffn_hidden_size or cfg.hidden_size * 4
            h = nn.Dense(ffn, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)),
                         name="dense_h_to_4h")(mlp_in)
            return nn.Dense(cfg.hidden_size, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)),
                            name="dense_4h_to_h")(jax.nn.gelu(h, approximate=False))

        if not cfg.parallel_attn:
            # falcon-rw sequential residual: ln1 → attn → add; ln2 → mlp → add
            attn_in = ln(name="input_layernorm")(x)
            h = x + FalconAttention(cfg, name="self_attention")(attn_in, positions, segment_ids)
            out = h + mlp(ln(name="post_attention_layernorm")(h))
            if self.scanned:
                return out, None
            return out

        if cfg.num_ln_in_parallel_attn == 2:  # HF keys purely on this flag
            attn_in = ln(name="ln_attn")(x)
            mlp_in = ln(name="ln_mlp")(x)
        else:
            # falcon-7b and falcon-11B (num_ln_in_parallel_attn=1): one LN
            # feeds both parallel branches
            attn_in = ln(name="input_layernorm")(x)
            mlp_in = attn_in
        attn_out = FalconAttention(cfg, name="self_attention")(attn_in, positions, segment_ids)
        out = x + attn_out + mlp(mlp_in)  # parallel residual
        if self.scanned:
            return out, None
        return out


class FalconForCausalLM(nn.Module):
    cfg: FalconConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="word_embeddings")
        x = embed(input_ids)
        block_cls = FalconBlock
        if cfg.remat:
            block_cls = nn.remat(FalconBlock, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls, variable_axes={"params": 0}, split_rngs={"params": True},
                             in_axes=(nn.broadcast, nn.broadcast), length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = blocks(cfg, scanned=True, name="h")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"h_{i}")(x, positions, segment_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln_f")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x)
        return nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                               name="lm_head")(x)
