"""Phi-1 / Phi-2 — parallel attention+MLP decoder with partial rotary.

ref: deepspeed/inference/v2/model_implementations/phi/ — LN(+bias) into
parallel attention and MLP branches sharing one residual, biases on every
projection, rotary applied only to the first ``rotary_dim`` of each head
(partial_rotary_factor), gelu MLP, final LN and a biased lm_head.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .llama import (EMBED, HEADS, HEAD_DIM, KV_HEADS, LAYERS, MLP, VOCAB, _logical,
                    get_attention_impl, rotary_embedding)


@dataclass(frozen=True)
class PhiConfig:
    vocab_size: int = 51200
    hidden_size: int = 2560
    intermediate_size: int = 10240
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    partial_rotary_factor: float = 0.4
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    max_position_embeddings: int = 2048
    tie_word_embeddings: bool = False
    qk_layernorm: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size,
                      hidden_size=hf_cfg.hidden_size,
                      intermediate_size=hf_cfg.intermediate_size,
                      num_hidden_layers=hf_cfg.num_hidden_layers,
                      num_attention_heads=hf_cfg.num_attention_heads,
                      num_key_value_heads=getattr(hf_cfg, "num_key_value_heads", None)
                      or hf_cfg.num_attention_heads,
                      partial_rotary_factor=getattr(hf_cfg, "partial_rotary_factor", 0.5),
                      rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
                      layer_norm_eps=getattr(hf_cfg, "layer_norm_eps", 1e-5),
                      max_position_embeddings=hf_cfg.max_position_embeddings,
                      tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
                      qk_layernorm=getattr(hf_cfg, "qk_layernorm", False))
        fields.update(overrides)
        return PhiConfig(**fields)


def apply_partial_rope(x, cos, sin, rotary_dim):
    """Rotate only the first ``rotary_dim`` of each head (HF phi
    rotate_half convention), pass the rest through.
    x: [B, S, N, D]; cos/sin: [B, S, rotary_dim/2]."""
    rot, keep = x[..., :rotary_dim].astype(jnp.float32), x[..., rotary_dim:]
    half = rotary_dim // 2
    r1, r2 = rot[..., :half], rot[..., half:]
    c, s = cos[:, :, None, :], sin[:, :, None, :]
    rotated = jnp.concatenate([r1 * c - r2 * s, r2 * c + r1 * s], axis=-1)
    return jnp.concatenate([rotated.astype(x.dtype), keep], axis=-1)


class PhiAttention(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        rot_dim = int(D * cfg.partial_rotary_factor)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        if cfg.qk_layernorm:
            # per-head LayerNorm over head_dim BEFORE rope (ref: HF PhiAttention
            # q_layernorm/k_layernorm, phi-1/phi-1.5 checkpoints)
            q = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="q_layernorm")(q)
            k = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="k_layernorm")(k)
        cos, sin = rotary_embedding(positions, rot_dim, cfg.rope_theta)
        q = apply_partial_rope(q, cos, sin, rot_dim)
        k = apply_partial_rope(k, cos, sin, rot_dim)
        out = get_attention_impl(cfg.attention_impl)(q, k, v, causal=True, segment_ids=segment_ids)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                               name="dense")(out)


class PhiBlock(nn.Module):
    cfg: PhiConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="input_layernorm")(x)
        attn_out = PhiAttention(cfg, name="self_attn")(h, positions, segment_ids)
        m = nn.Dense(cfg.intermediate_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)), name="fc1")(h)
        m = jax.nn.gelu(m, approximate=True)  # HF phi: gelu_new
        mlp_out = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)), name="fc2")(m)
        out = x + attn_out + mlp_out  # parallel residual
        if self.scanned:
            return out, None
        return out


class PhiForCausalLM(nn.Module):
    cfg: PhiConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        block_cls = PhiBlock
        if cfg.remat:
            block_cls = nn.remat(PhiBlock, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls, variable_axes={"params": 0}, split_rngs={"params": True},
                             in_axes=(nn.broadcast, nn.broadcast), length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = blocks(cfg, scanned=True, name="layers")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, positions, segment_ids)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="final_layernorm")(x)
        return nn.Dense(cfg.vocab_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                        name="lm_head")(x)
