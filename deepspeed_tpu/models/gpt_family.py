"""v1-era GPT-family causal LMs: BLOOM, GPT-NeoX, GPT-J, GPT-Neo.

Reference coverage: ``deepspeed/module_inject/containers/{bloom,gptneox,
gptj,gptneo}.py`` — the reference serves these through v1 kernel-injection
containers; here each is a native flax model sharing the Llama stack's
design (scan-over-layers, logical-axis params, pluggable attention) with
its family's quirks implemented exactly:

  * BLOOM — ALiBi position bias (added UNSCALED to the scaled scores, HF
    baddbmm semantics), fused qkv in (head, 3, dim) layout, LN after the
    word embedding, sequential residual, tied head.
  * GPT-NeoX — partial neox-style (half-split) rotary over
    ``rotary_pct·D`` dims, fused qkv in (head, 3·dim) layout, parallel
    residual (use_parallel_residual), untied embed_out.
  * GPT-J — partial INTERLEAVED (rotate-every-two) rotary over
    ``rotary_dim`` dims, one shared LN feeding both parallel branches,
    biased lm_head.
  * GPT-Neo — GPT-2-style learned positions, alternating global/local
    attention layers (window_size) realized as a per-layer window array
    scanned through one compiled body, untied... tied head.
"""

import dataclasses
from functools import partial
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .falcon import alibi_slopes
from .llama import (EMBED, HEAD_DIM, HEADS, LAYERS, MLP, VOCAB, _logical,
                    get_attention_impl, reference_attention, rotary_embedding)
from .phi import apply_partial_rope

POSITIONS = "positions"


def apply_rope_interleaved(x, positions, rotary_dim, theta=10000.0):
    """GPT-J rotary: rotate-every-two pairing over the first ``rotary_dim``
    dims (HF apply_rotary_pos_emb with duplicate_interleave), rest pass
    through.  x: [B, S, N, D]."""
    rot = x[..., :rotary_dim].astype(jnp.float32)
    keep = x[..., rotary_dim:]
    inv_freq = 1.0 / (theta**(jnp.arange(0, rotary_dim, 2, dtype=jnp.float32) / rotary_dim))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq          # [B, S, rd/2]
    sin = jnp.repeat(jnp.sin(ang), 2, axis=-1)[:, :, None, :]          # duplicate_interleave
    cos = jnp.repeat(jnp.cos(ang), 2, axis=-1)[:, :, None, :]
    x1 = rot[..., ::2]
    x2 = rot[..., 1::2]
    rot_ev = jnp.stack([-x2, x1], axis=-1).reshape(rot.shape)
    out = rot * cos + rot_ev * sin
    return jnp.concatenate([out.astype(x.dtype), keep], axis=-1)


def _ln(cfg, name):
    return nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        scale_init=_logical(nn.initializers.ones_init(), (EMBED, )),
                        bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )), name=name)


def _dense(cfg, feats, names, name, bias=True):
    return nn.DenseGeneral(features=feats, use_bias=bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           kernel_init=_logical(nn.initializers.normal(0.02), names),
                           bias_init=_logical(nn.initializers.zeros_init(),
                                              names[1:] if isinstance(feats, tuple) else (names[-1], )),
                           name=name)


def _mlp_gelu(cfg, x, inter, names=("dense_h_to_4h", "dense_4h_to_h"), bias=True):
    h = _dense(cfg, inter, (EMBED, MLP), names[0], bias)(x)
    return _dense(cfg, cfg.hidden_size, (MLP, EMBED), names[1], bias)(nn.gelu(h, approximate=True))


def _scan_blocks(block_cls, cfg, n_layers, extra_in_axes=()):
    # non-carry args are (positions, *extra, segment_ids): positions and
    # segment_ids broadcast; extras (e.g. GPT-Neo's per-layer window) scan
    return nn.scan(block_cls, variable_axes={"params": 0}, split_rngs={"params": True},
                   in_axes=(nn.broadcast, ) + extra_in_axes + (nn.broadcast, ), length=n_layers,
                   metadata_params={nn.PARTITION_NAME: LAYERS})


# ------------------------------------------------------------------- BLOOM


@dataclasses.dataclass(frozen=True)
class BloomConfig:
    vocab_size: int = 250880
    hidden_size: int = 64
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
                      num_hidden_layers=hf_cfg.n_layer, num_attention_heads=hf_cfg.n_head,
                      layer_norm_epsilon=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
                      tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", True))
        fields.update(overrides)
        return BloomConfig(**fields)


class BloomAttention(nn.Module):
    cfg: BloomConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        qkv = _dense(cfg, (H, 3, D), (EMBED, HEADS, None, HEAD_DIM), "query_key_value")(x)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        # HF bloom: scores = alibi + (q·kᵀ)/√D — the alibi bias is NOT
        # scaled (baddbmm beta=1, alpha=inv_norm), unlike falcon
        slopes = jnp.asarray(alibi_slopes(H))
        kpos = positions.astype(jnp.float32)
        bias = slopes[None, :, None, None] * kpos[:, None, None, :]
        out = reference_attention(q, k, v, causal=True, segment_ids=segment_ids, attn_bias=bias)
        return nn.DenseGeneral(
            features=cfg.hidden_size, axis=(-2, -1), use_bias=True, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
            bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )), name="dense")(out)


class BloomBlock(nn.Module):
    cfg: BloomConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        h = x + BloomAttention(cfg, name="self_attention")(
            _ln(cfg, "input_layernorm")(x), positions, segment_ids)
        out = h + _mlp_gelu(cfg, _ln(cfg, "post_attention_layernorm")(h), 4 * cfg.hidden_size)
        return (out, None) if self.scanned else out


class BloomForCausalLM(nn.Module):
    """ref: module_inject/containers/bloom.py (BLOOMLayerPolicy)."""
    cfg: BloomConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="word_embeddings")
        x = _ln(cfg, "word_embeddings_layernorm")(embed(input_ids))
        if cfg.scan_layers:
            x, _ = _scan_blocks(BloomBlock, cfg, cfg.num_hidden_layers)(
                cfg, scanned=True, name="h")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = BloomBlock(cfg, name=f"h_{i}")(x, positions, segment_ids)
        x = _ln(cfg, "ln_f")(x)
        return embed.attend(x)


# ---------------------------------------------------------------- GPT-NeoX


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 64
    intermediate_size: int = 256
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    use_parallel_residual: bool = True
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
                      intermediate_size=hf_cfg.intermediate_size,
                      num_hidden_layers=hf_cfg.num_hidden_layers,
                      num_attention_heads=hf_cfg.num_attention_heads,
                      rotary_pct=getattr(hf_cfg, "rotary_pct", 0.25),
                      rope_theta=getattr(hf_cfg, "rotary_emb_base", 10000.0),
                      use_parallel_residual=getattr(hf_cfg, "use_parallel_residual", True),
                      layer_norm_epsilon=getattr(hf_cfg, "layer_norm_eps", 1e-5))
        fields.update(overrides)
        return GPTNeoXConfig(**fields)


class GPTNeoXBlock(nn.Module):
    cfg: GPTNeoXConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        rot = int(D * cfg.rotary_pct)

        def attn(a_in):
            qkv = _dense(cfg, (H, 3, D), (EMBED, HEADS, None, HEAD_DIM),
                         "query_key_value")(a_in)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            cos, sin = rotary_embedding(positions, rot, cfg.rope_theta)
            q = apply_partial_rope(q, cos, sin, rot)
            k = apply_partial_rope(k, cos, sin, rot)
            out = get_attention_impl(cfg.attention_impl)(q, k, v, causal=True, segment_ids=segment_ids)
            return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
                                   bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                                   name="dense")(out)

        attn_out = attn(_ln(cfg, "input_layernorm")(x))
        if cfg.use_parallel_residual:
            mlp_out = _mlp_gelu(cfg, _ln(cfg, "post_attention_layernorm")(x), cfg.intermediate_size)
            out = x + attn_out + mlp_out
        else:
            h = x + attn_out
            out = h + _mlp_gelu(cfg, _ln(cfg, "post_attention_layernorm")(h), cfg.intermediate_size)
        return (out, None) if self.scanned else out


class GPTNeoXForCausalLM(nn.Module):
    """ref: module_inject/containers/gptneox.py (GPTNEOXLayerPolicy)."""
    cfg: GPTNeoXConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                     name="embed_in")(input_ids)
        if cfg.scan_layers:
            x, _ = _scan_blocks(GPTNeoXBlock, cfg, cfg.num_hidden_layers)(
                cfg, scanned=True, name="layers")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = GPTNeoXBlock(cfg, name=f"layers_{i}")(x, positions, segment_ids)
        x = _ln(cfg, "final_layer_norm")(x)
        return nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, VOCAB)),
                               name="embed_out")(x)


# ------------------------------------------------------------------- GPT-J


@dataclasses.dataclass(frozen=True)
class GPTJConfig:
    vocab_size: int = 50400
    hidden_size: int = 64
    intermediate_size: int = 256
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    rotary_dim: int = 8
    layer_norm_epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.n_embd,
                      intermediate_size=getattr(hf_cfg, "n_inner", None) or 4 * hf_cfg.n_embd,
                      num_hidden_layers=hf_cfg.n_layer, num_attention_heads=hf_cfg.n_head,
                      rotary_dim=getattr(hf_cfg, "rotary_dim", None) or hf_cfg.n_embd // hf_cfg.n_head,
                      layer_norm_epsilon=getattr(hf_cfg, "layer_norm_epsilon", 1e-5))
        fields.update(overrides)
        return GPTJConfig(**fields)


class GPTJBlock(nn.Module):
    cfg: GPTJConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        a_in = _ln(cfg, "ln_1")(x)   # ONE shared LN feeds both parallel branches

        proj = lambda name: nn.DenseGeneral(
            features=(H, D), use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, HEADS, HEAD_DIM)), name=name)
        q = apply_rope_interleaved(proj("q_proj")(a_in), positions, cfg.rotary_dim)
        k = apply_rope_interleaved(proj("k_proj")(a_in), positions, cfg.rotary_dim)
        v = proj("v_proj")(a_in)
        out = get_attention_impl(cfg.attention_impl)(q, k, v, causal=True, segment_ids=segment_ids)
        attn_out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=False,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
                                   name="out_proj")(out)
        mlp_out = _mlp_gelu(cfg, a_in, cfg.intermediate_size, names=("fc_in", "fc_out"))
        out = x + attn_out + mlp_out
        return (out, None) if self.scanned else out


class GPTJForCausalLM(nn.Module):
    """ref: module_inject/containers/gptj.py (HFGPTJLayerPolicy)."""
    cfg: GPTJConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        x = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                     name="wte")(input_ids)
        if cfg.scan_layers:
            x, _ = _scan_blocks(GPTJBlock, cfg, cfg.num_hidden_layers)(
                cfg, scanned=True, name="h")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = GPTJBlock(cfg, name=f"h_{i}")(x, positions, segment_ids)
        x = _ln(cfg, "ln_f")(x)
        # HF GPT-J lm_head carries a bias (unusual among the GPT family)
        return nn.DenseGeneral(features=cfg.vocab_size, use_bias=True, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, VOCAB)),
                               bias_init=_logical(nn.initializers.zeros_init(), (VOCAB, )),
                               name="lm_head")(x)


# ------------------------------------------------------------------ GPT-Neo


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig:
    vocab_size: int = 50257
    hidden_size: int = 64
    intermediate_size: int = 256
    num_hidden_layers: int = 2
    num_attention_heads: int = 8
    max_position_embeddings: int = 2048
    attention_layers: Tuple[str, ...] = ("global", "local")
    window_size: int = 256
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = False

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size, hidden_size=hf_cfg.hidden_size,
                      intermediate_size=getattr(hf_cfg, "intermediate_size", None) or 4 * hf_cfg.hidden_size,
                      num_hidden_layers=hf_cfg.num_layers, num_attention_heads=hf_cfg.num_heads,
                      max_position_embeddings=hf_cfg.max_position_embeddings,
                      attention_layers=tuple(hf_cfg.attention_layers),
                      window_size=getattr(hf_cfg, "window_size", 256),
                      layer_norm_epsilon=getattr(hf_cfg, "layer_norm_epsilon", 1e-5))
        fields.update(overrides)
        return GPTNeoConfig(**fields)


def _windowed_attention(q, k, v, window, segment_ids=None):
    """Causal attention whose local window is a TRACED per-layer value
    (window <= 0 means global) — this is what lets GPT-Neo's alternating
    global/local stack ride ONE scanned layer body instead of unrolling.
    NO 1/sqrt(D) score scaling: GPT-Neo was trained without it (HF
    GPTNeoSelfAttention omits the division)."""
    b, sq, nh, hd = q.shape
    logits = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32), k.astype(jnp.float32))
    qpos = jnp.arange(sq)[:, None]
    kpos = jnp.arange(sq)[None, :]
    eff = jnp.where(window > 0, window, sq + 1)
    mask = (qpos >= kpos) & (kpos > qpos - eff)
    logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnqk,bknd->bqnd", probs.astype(v.dtype), v)


class GPTNeoBlock(nn.Module):
    cfg: GPTNeoConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, window, segment_ids=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        a_in = _ln(cfg, "ln_1")(x)
        proj = lambda name: nn.DenseGeneral(
            features=(H, D), use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, HEADS, HEAD_DIM)), name=name)
        out = _windowed_attention(proj("q_proj")(a_in), proj("k_proj")(a_in), proj("v_proj")(a_in),
                                  window, segment_ids)
        attn_out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                                   dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                                   kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
                                   bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                                   name="out_proj")(out)
        h = x + attn_out
        out_ = h + _mlp_gelu(cfg, _ln(cfg, "ln_2")(h), cfg.intermediate_size, names=("c_fc", "c_proj"))
        return (out_, None) if self.scanned else out_


class GPTNeoForCausalLM(nn.Module):
    """ref: module_inject/containers/gptneo.py (HFGPTNEOLayerPolicy)."""
    cfg: GPTNeoConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        wte = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                       embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                       name="wte")
        wpe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype,
                       embedding_init=_logical(nn.initializers.normal(0.01), (POSITIONS, EMBED)),
                       name="wpe")
        x = wte(input_ids) + wpe(positions)
        # per-layer window as scanned data: "local" layers attend the last
        # window_size keys, "global" layers the whole causal prefix
        layer_types = [cfg.attention_layers[i % len(cfg.attention_layers)]
                       for i in range(cfg.num_hidden_layers)]
        windows = jnp.asarray([cfg.window_size if t == "local" else 0 for t in layer_types],
                              jnp.int32)
        if cfg.scan_layers:
            x, _ = _scan_blocks(GPTNeoBlock, cfg, cfg.num_hidden_layers, extra_in_axes=(0, ))(
                cfg, scanned=True, name="h")(x, positions, windows, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = GPTNeoBlock(cfg, name=f"h_{i}")(x, positions, windows[i], segment_ids)
        x = _ln(cfg, "ln_f")(x)
        return wte.attend(x)
