"""Qwen2-MoE — llama-style attention (qkv bias) + sparse MoE MLP with a
shared expert.

ref: deepspeed/inference/v2/model_implementations/qwen_v2_moe/.  Per block:
softmax-over-all-experts gating → top-k (optionally renormalized), experts
are gated-SiLU MLPs at ``moe_intermediate_size``, plus a dense shared
expert scaled by sigmoid(shared_expert_gate(x)).

The expert mixture here is the exact dense formulation (every expert's
output weighted by its routing weight, zeros for non-selected) — bit-exact
with HF's gather-based compute and MXU-friendly via stacked-expert einsums.
For large expert counts sharded over the mesh's expert axis, use
deepspeed_tpu.moe.MoE (all-to-all dispatch with capacity) — this model
targets checkpoint parity and fine-tuning.
"""

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .llama import EMBED, LAYERS, MLP, VOCAB, LlamaAttention, LlamaConfig, RMSNorm, _logical
from ..axes import EXPERT_EMBED, EXPERT_MLP, EXPERTS


@dataclass(frozen=True)
class Qwen2MoeConfig:
    vocab_size: int = 151936
    hidden_size: int = 2048
    intermediate_size: int = 5632          # dense (unused when all-sparse)
    moe_intermediate_size: int = 1408
    shared_expert_intermediate_size: int = 5632
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 60
    num_experts_per_tok: int = 4
    norm_topk_prob: bool = False
    mlp_only_layers: tuple = ()   # HF mlp_only_layers: dense-MLP layer indices
    decoder_sparse_step: int = 1  # HF: layer i is sparse iff (i+1) % step == 0
    qkv_bias: bool = True
    max_position_embeddings: int = 8192
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = "reference"

    def as_llama(self) -> LlamaConfig:
        return LlamaConfig(vocab_size=self.vocab_size, hidden_size=self.hidden_size,
                           intermediate_size=self.moe_intermediate_size,
                           num_hidden_layers=self.num_hidden_layers,
                           num_attention_heads=self.num_attention_heads,
                           num_key_value_heads=self.num_key_value_heads,
                           max_position_embeddings=self.max_position_embeddings,
                           rope_theta=self.rope_theta, rms_norm_eps=self.rms_norm_eps,
                           dtype=self.dtype, param_dtype=self.param_dtype,
                           attention_impl=self.attention_impl, attention_bias=self.qkv_bias)

    def layer_is_sparse(self, i: int) -> bool:
        """HF Qwen2MoeDecoderLayer rule: dense MLP for mlp_only_layers and
        off-step layers, sparse MoE otherwise."""
        return (i not in tuple(self.mlp_only_layers) and self.num_experts > 0
                and (i + 1) % max(1, self.decoder_sparse_step) == 0)

    @property
    def mixed_stack(self) -> bool:
        return any(not self.layer_is_sparse(i) for i in range(self.num_hidden_layers))

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(vocab_size=hf_cfg.vocab_size,
                      hidden_size=hf_cfg.hidden_size,
                      intermediate_size=hf_cfg.intermediate_size,
                      moe_intermediate_size=hf_cfg.moe_intermediate_size,
                      shared_expert_intermediate_size=hf_cfg.shared_expert_intermediate_size,
                      num_hidden_layers=hf_cfg.num_hidden_layers,
                      num_attention_heads=hf_cfg.num_attention_heads,
                      num_key_value_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
                      num_experts=hf_cfg.num_experts,
                      num_experts_per_tok=hf_cfg.num_experts_per_tok,
                      norm_topk_prob=getattr(hf_cfg, "norm_topk_prob", False),
                      qkv_bias=getattr(hf_cfg, "qkv_bias", True),
                      max_position_embeddings=hf_cfg.max_position_embeddings,
                      rope_theta=getattr(hf_cfg, "rope_theta", 1e6),
                      rms_norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-6),
                      tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
                      mlp_only_layers=tuple(getattr(hf_cfg, "mlp_only_layers", None) or ()),
                      decoder_sparse_step=getattr(hf_cfg, "decoder_sparse_step", 1))
        fields.update(overrides)
        cfg = Qwen2MoeConfig(**fields)
        if cfg.mixed_stack and cfg.scan_layers:
            # mixed dense/sparse layers can't share one scanned body
            cfg = Qwen2MoeConfig(**{**cfg.__dict__, "scan_layers": False})
        return cfg


def _moe_intermediate_constraint(t):
    """Pin a [B, S, N_experts, *] dense-mixture intermediate to batch
    sharding over the full (data×expert) group, experts LOCAL.

    This model computes every expert for every token (exact HF math — see
    the module docstring; capacity-based EP dispatch lives in moe.MoE), so
    the FLOP-consistent layout is token-parallel over all DP axes with the
    expert-stacked weights gathered per layer, exactly like ZeRO-3 dense
    weights.  Leaving GSPMD to resolve the chain instead mixes the weights'
    expert-axis sharding into the activations and falls back to
    "Involuntary full rematerialization" — replicating a full [B,S,NE,E]
    tensor per MoE layer (MULTICHIP_r03.json tail)."""
    from ..comm.mesh import BATCH_AXES, get_global_mesh, has_global_mesh
    from .llama import _skip_constraint
    if not has_global_mesh() or _skip_constraint(t):
        return t
    mesh = get_global_mesh()
    nb = int(np.prod([mesh.shape.get(a, 1) for a in BATCH_AXES]))
    if nb == 1 or t.shape[0] % nb:
        return t
    from jax.sharding import NamedSharding, PartitionSpec
    spec = [None] * t.ndim
    spec[0] = BATCH_AXES
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, PartitionSpec(*spec)))


class Qwen2MoeSparseMLP(nn.Module):
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        E, NE, M = cfg.hidden_size, cfg.num_experts, cfg.moe_intermediate_size
        dt = cfg.dtype

        gate_logits = nn.Dense(NE, use_bias=False, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                               name="gate")(x.astype(jnp.float32))         # [B,S,NE]
        probs = jax.nn.softmax(gate_logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, cfg.num_experts_per_tok)
        if cfg.norm_topk_prob:
            topv = topv / (topv.sum(-1, keepdims=True) + 1e-20)
        # dense routing weights: zeros except selected experts
        onehot = jax.nn.one_hot(topi, NE, dtype=probs.dtype)   # [B,S,K,NE]
        weights = (onehot * topv[..., None]).sum(-2)           # [B,S,NE]

        # EXPERT_EMBED/EXPERT_MLP exclude the expert mesh axis from the ZeRO
        # dims — the 'expert' axis is already consumed by the EXPERTS dim
        # (see moe/experts.py + module_inject/tp_rules.py)
        w_gate = self.param("w_gate", _logical(nn.initializers.lecun_normal(), (EXPERTS, EXPERT_EMBED, EXPERT_MLP)),
                            (NE, E, M), cfg.param_dtype)
        w_up = self.param("w_up", _logical(nn.initializers.lecun_normal(), (EXPERTS, EXPERT_EMBED, EXPERT_MLP)),
                          (NE, E, M), cfg.param_dtype)
        w_down = self.param("w_down", _logical(nn.initializers.lecun_normal(), (EXPERTS, EXPERT_MLP, EXPERT_EMBED)),
                            (NE, M, E), cfg.param_dtype)
        # dense mixture: every expert evaluated, weighted-summed (exact HF math)
        h = _moe_intermediate_constraint(jnp.einsum("bse,nem->bsnm", x.astype(dt), w_gate.astype(dt)))
        u = _moe_intermediate_constraint(jnp.einsum("bse,nem->bsnm", x.astype(dt), w_up.astype(dt)))
        act = nn.silu(h) * u
        y = _moe_intermediate_constraint(jnp.einsum("bsnm,nme->bsne", act, w_down.astype(dt)))
        out = jnp.einsum("bsne,bsn->bse", y.astype(jnp.float32), weights)
        from .llama import activation_constraint
        out = activation_constraint(out)

        # shared expert with sigmoid gate (HF: shared_expert_gate Linear(E,1))
        # shared kernels use the EXPERT-family EMBED rule (fsdp minus the
        # expert axis): inside this block the expert weights already exclude
        # 'expert' from their ZeRO dims, and mixing both conventions makes
        # the scan backward reshard the shared kernels' grads through an
        # SPMD involuntary full remat (r4 dryrun guard)
        sh = nn.Dense(cfg.shared_expert_intermediate_size, use_bias=False, dtype=dt,
                      param_dtype=cfg.param_dtype,
                      kernel_init=_logical(nn.initializers.lecun_normal(), (EXPERT_EMBED, MLP)),
                      name="shared_gate_proj")(x)
        su = nn.Dense(cfg.shared_expert_intermediate_size, use_bias=False, dtype=dt,
                      param_dtype=cfg.param_dtype,
                      kernel_init=_logical(nn.initializers.lecun_normal(), (EXPERT_EMBED, MLP)),
                      name="shared_up_proj")(x)
        sd = nn.Dense(E, use_bias=False, dtype=dt, param_dtype=cfg.param_dtype,
                      kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EXPERT_EMBED)),
                      name="shared_down_proj")(nn.silu(sh) * su)
        sgate = nn.Dense(1, use_bias=False, dtype=jnp.float32, param_dtype=cfg.param_dtype,
                         name="shared_expert_gate")(x.astype(jnp.float32))
        out = out + jax.nn.sigmoid(sgate) * sd.astype(jnp.float32)
        return out.astype(x.dtype)


class Qwen2MoeDenseMLP(nn.Module):
    """SwiGLU dense MLP for mlp_only/off-step layers (ref: HF Qwen2MoeMLP
    with config.intermediate_size)."""
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, names, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            kernel_init=_logical(nn.initializers.lecun_normal(), names), name=name)
        g = dense(cfg.intermediate_size, (EMBED, MLP), "gate_proj")(x)
        u = dense(cfg.intermediate_size, (EMBED, MLP), "up_proj")(x)
        return dense(cfg.hidden_size, (MLP, EMBED), "down_proj")(nn.silu(g) * u)


class Qwen2MoeBlock(nn.Module):
    cfg: Qwen2MoeConfig
    scanned: bool = False
    sparse: bool = True

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        lcfg = cfg.as_llama()
        h = x + LlamaAttention(lcfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x),
            positions, segment_ids)
        mlp = Qwen2MoeSparseMLP(cfg, name="mlp") if self.sparse else Qwen2MoeDenseMLP(cfg, name="mlp")
        out = h + mlp(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="post_attention_layernorm")(h))
        if self.scanned:
            return out, None
        return out


class Qwen2MoeForCausalLM(nn.Module):
    cfg: Qwen2MoeConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        block_cls = Qwen2MoeBlock
        if cfg.remat:
            block_cls = nn.remat(Qwen2MoeBlock, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls, variable_axes={"params": 0}, split_rngs={"params": True},
                             in_axes=(nn.broadcast, nn.broadcast), length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = blocks(cfg, scanned=True, name="layers")(x, positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, sparse=cfg.layer_is_sparse(i), name=f"layers_{i}")(x, positions, segment_ids)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x)
        return nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                               name="lm_head")(x)
