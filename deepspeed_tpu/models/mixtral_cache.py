"""Mixtral with paged KV cache — the serving twin of models/mixtral.py.

ref: deepspeed/inference/v2/model_implementations/mixtral/policy.py:1 (+
model.py) — the reference's marquee FastGen MoE target.  Same contract as
``LlamaForCausalLMWithCache``: one chunked forward serving prefill /
continuation / decode with the KV arena threaded through, except the dense
SwiGLU MLP is the top-k-routed expert bank.  Routing at serving time runs
``train=False`` (eval capacity factor, no gating noise) and the router aux
loss is discarded.

Param-tree compatibility: names mirror MixtralForCausalLM exactly
(embed_tokens, layers/{self_attn, input_layernorm, post_attention_layernorm,
block_sparse_moe/{gate, experts}}, norm, lm_head), so checkpoints converted
by MixtralPolicy.convert — or trained with the training model — apply
unchanged.
"""

import jax.numpy as jnp
from flax import linen as nn

from ..moe.layer import MoE
from .llama import EMBED, LAYERS, VOCAB, RMSNorm, _logical
from .llama_cache import LlamaAttentionCache
from .mixtral import MixtralConfig


class MixtralBlockCache(nn.Module):
    cfg: MixtralConfig
    page_size: int = 16
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        cfg = self.cfg
        x = carry
        attn_out, layer_pages = LlamaAttentionCache(cfg.as_llama(), self.page_size, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x), positions,
            layer_pages, block_table, start_pos, chunk_lens)
        h = x + attn_out
        moe_out, _l_aux, _counts = MoE(hidden_size=cfg.hidden_size,
                                       num_experts=cfg.num_local_experts,
                                       intermediate_size=cfg.intermediate_size,
                                       k=cfg.num_experts_per_tok,
                                       capacity_factor=cfg.capacity_factor,
                                       eval_capacity_factor=cfg.eval_capacity_factor,
                                       min_capacity=cfg.min_capacity,
                                       drop_tokens=cfg.drop_tokens,
                                       dtype=cfg.dtype,
                                       param_dtype=cfg.param_dtype,
                                       name="block_sparse_moe")(
                                           RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype,
                                                   name="post_attention_layernorm")(h), train=False)
        out = h + moe_out
        return out, layer_pages


class MixtralForCausalLMWithCache(nn.Module):
    """Chunked forward with paged KV over the MoE stack.  ``apply(variables,
    tokens, start_pos, block_table, cache)`` → (logits, new_cache)."""
    cfg: MixtralConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        embed = nn.Embed(num_embeddings=cfg.vocab_size,
                         features=cfg.hidden_size,
                         dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        blocks = nn.scan(MixtralBlockCache,
                         variable_axes={"params": 0},
                         split_rngs={"params": True},
                         in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                         out_axes=0,
                         length=cfg.num_hidden_layers,
                         metadata_params={nn.PARTITION_NAME: LAYERS})
        x, cache = blocks(cfg, self.page_size, scanned=True,
                          name="layers")(x, cache, positions, block_table, start_pos, chunk_lens)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        logits = nn.DenseGeneral(features=cfg.vocab_size,
                                 use_bias=False,
                                 dtype=cfg.dtype,
                                 param_dtype=cfg.param_dtype,
                                 kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                 name="lm_head")(x)
        return logits, cache
