"""Llama with paged KV cache — the inference-path twin of models/llama.py.

Reference: the v1 kernel-injection containers keep KV in a global inference
context arena (``csrc/transformer/inference/includes/inference_context.h``)
and v2 FastGen uses a blocked KV cache with blocked-flash kernels
(``deepspeed/inference/v2/ragged/kv_cache.py:40 BlockedKVCache``,
``inference/v2/kernels/ragged_ops``).  TPU-native realisation: the cache is
an explicit JAX array arena of fixed-size pages, functionally threaded
through the forward pass (donated between steps so XLA updates it in
place); attention gathers a sequence's pages via its block table.

Param-tree compatibility: module/submodule names mirror LlamaForCausalLM
exactly (embed_tokens, model/layers/{self_attn/{q,k,v,o}_proj,
input_layernorm, post_attention_layernorm, mlp/{gate,up,down}_proj}, norm,
lm_head), so weights trained with the training model apply unchanged.

One program serves prefill chunks, continuation chunks and decode (C=1) —
the Dynamic-SplitFuse property that all phases are the same computation at
different chunk sizes (ref: blogs/deepspeed-fastgen — SplitFuse; here it
falls out of the unified chunked forward).
"""

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import (EMBED, HEAD_DIM, HEADS, KV_HEADS, LAYERS, MLP, VOCAB, LlamaConfig, LlamaMLP, RMSNorm, _logical,
                    apply_rope, rotary_embedding)


@dataclasses.dataclass(frozen=True)
class PagedKVConfig:
    """Cache geometry (ref: inference/v2/ragged/manager_configs.py)."""
    num_pages: int = 128
    page_size: int = 16
    max_pages_per_seq: int = 8


def init_kv_cache(cfg, kv: PagedKVConfig, dtype=jnp.bfloat16):
    """Allocate the paged arena: [L, P, page, 2, n_kv, hd].  Page 0 is the
    reserved null page (block tables point unused slots at it).  Works for
    any model-family config (falcon names its kv-head count differently;
    MHA models have none)."""
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    n_kv = getattr(cfg, "num_key_value_heads", None) or getattr(cfg, "num_kv_heads", None) \
        or cfg.num_attention_heads
    return jnp.zeros((cfg.num_hidden_layers, kv.num_pages, kv.page_size, 2, n_kv, head_dim),
                     dtype)


def _write_pages(pages, k_new, v_new, block_table, start_pos, page_size, chunk_lens=None):
    """Scatter a chunk's K/V into the arena pages.

    pages: [P, page, 2, n_kv, hd] (one layer)   k/v_new: [B, C, n_kv, hd]
    block_table: [B, max_pages]  start_pos: [B]  chunk_lens: [B] or None —
    positions at/after a row's chunk_len are padding; their writes are
    redirected to the reserved null page 0.
    """
    b, c = k_new.shape[0], k_new.shape[1]
    positions = start_pos[:, None] + jnp.arange(c)[None, :]          # [B, C]
    # page lookup must stay in-bounds for the pad region too (out-of-range
    # take_along_axis would read junk pages)
    page_slot = jnp.minimum(positions // page_size, block_table.shape[1] - 1)
    page_idx = jnp.take_along_axis(block_table, page_slot, axis=1)   # [B, C]
    kv_chunk = jnp.stack([k_new, v_new], axis=2)                      # [B, C, 2, n_kv, hd]
    if chunk_lens is not None:
        valid = jnp.arange(c)[None, :] < chunk_lens[:, None]          # [B, C]
        page_idx = jnp.where(valid, page_idx, 0)
        # ALSO zero the redirected values: pad-region activations can be
        # non-finite (e.g. out-of-range learned-position lookups fill NaN),
        # and a NaN-poisoned null page turns masked attention into NaN via
        # 0 * NaN in the probs @ V matmul
        kv_chunk = jnp.where(valid[:, :, None, None, None], kv_chunk, 0)
    slot_idx = positions % page_size                                  # [B, C]
    flat_kv = kv_chunk.reshape((-1, ) + kv_chunk.shape[2:])           # [B*C, 2, n_kv, hd]
    return pages.at[page_idx.reshape(-1), slot_idx.reshape(-1)].set(flat_kv)


def paged_attention(q, pages, block_table, start_pos, chunk_lens, page_size, sliding_window=0,
                    alibi_slopes=None):
    """Attention of a chunk's queries against (history + chunk) keys.

    q: [B, C, H, hd] (RoPE applied); pages: [P, page, 2, n_kv, hd] with the
    chunk's K/V already written; block_table: [B, max_pages]; start_pos: [B]
    = context length before this chunk; chunk_lens: [B] or None — query rows
    at/after a row's chunk_len (ragged padding) get zero output.
    ``alibi_slopes`` [H]: falcon-rw per-key position bias slope·kpos·scale
    (softmax is row-shift invariant, so the per-key form matches HF's
    build_alibi_tensor — same folding as models/falcon.py's training path).
    jnp reference implementation — the Pallas blocked-decode kernel slots in
    behind the same signature (ops/paged_attention.py).
    """
    b, c, h, d = q.shape
    max_pages = block_table.shape[1]
    n_kv = pages.shape[3]
    gathered = pages[block_table.reshape(-1)]                         # [B*maxp, page, 2, n_kv, hd]
    gathered = gathered.reshape(b, max_pages * page_size, 2, n_kv, d)
    k = gathered[:, :, 0]                                             # [B, S_kv, n_kv, hd]
    v = gathered[:, :, 1]
    if n_kv != h:
        rep = h // n_kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    logits = jnp.einsum("bcnd,bknd->bnck", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    qpos = start_pos[:, None] + jnp.arange(c)[None, :]                # [B, C]
    kpos = jnp.arange(max_pages * page_size)[None, :]                 # [1, S_kv]
    if alibi_slopes is not None:
        # HF adds alibi to RAW scores pre-scaling → fold the scale in
        bias = alibi_slopes.astype(jnp.float32)[None, :, None, None] * \
            kpos[0].astype(jnp.float32)[None, None, None, :] * scale
        logits = logits + bias
    mask = kpos[:, None, :] <= qpos[..., None]                        # [B, C, S_kv]
    if sliding_window and sliding_window > 0:  # mistral window (decode path)
        mask = mask & (kpos[:, None, :] > qpos[..., None] - sliding_window)
    logits = jnp.where(mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bnck,bknd->bcnd", probs.astype(v.dtype), v)
    if chunk_lens is not None:
        valid = jnp.arange(c)[None, :] < chunk_lens[:, None]          # [B, C]
        out = jnp.where(valid[..., None, None], out, 0)
    return out


def paged_attention_core(q, k, v, pages, block_table, start_pos, chunk_lens, page_size,
                         attention_impl="reference", sliding_window=0, alibi_slopes=None):
    """Shared paged-KV attention core for every model family's cache twin:
    write this chunk's K/V into the arena, then attend the chunk's queries
    against (history + chunk).  q/k/v are post-projection, post-RoPE
    [B, C, N(H|KV), D].  Returns (out [B, C, H, D], new_pages)."""
    pages = _write_pages(pages, k.astype(pages.dtype), v.astype(pages.dtype), block_table,
                         start_pos, page_size, chunk_lens)
    if attention_impl == "flash" and not sliding_window and alibi_slopes is None:
        from ..ops.paged_attention import paged_attention_pallas
        out = paged_attention_pallas(q, pages, block_table, start_pos, chunk_lens, page_size)
    else:
        # window masks / alibi bias decode through the jnp path (in-kernel
        # variants land with the kernel)
        out = paged_attention(q, pages, block_table, start_pos, chunk_lens, page_size,
                              sliding_window=sliding_window, alibi_slopes=alibi_slopes)
    return out, pages


def unstack_layer_params(variables, num_layers):
    """Convert scan-stacked params (``model/layers/*`` leaves ``[L, ...]``)
    to the unrolled layout (``model/layers_{i}/*``).

    The training↔serving layout converter: checkpoints trained with
    ``scan_layers=True`` (the training default) serve through the unrolled
    decode trunk without re-export (r3 verdict: scan-only cache twins
    blocked ``scan_layers=False`` serving).  No data movement — each
    unrolled leaf is a view-slice of the stacked leaf."""
    had_wrapper = isinstance(variables, dict) and "params" in variables
    p = dict(variables["params"]) if had_wrapper else dict(variables)
    m = dict(p.get("model", {}))
    if "layers" not in m:
        return variables  # already unrolled (or a foreign tree) — no-op
    stacked = m.pop("layers")
    for i in range(num_layers):
        m[f"layers_{i}"] = jax.tree.map(lambda x, i=i: x[i], stacked)
    p["model"] = m
    return {"params": p} if had_wrapper else p


class LlamaAttentionCache(nn.Module):
    cfg: LlamaConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, x, positions, pages, block_table, start_pos, chunk_lens=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        from functools import partial
        dense = partial(nn.DenseGeneral, use_bias=cfg.attention_bias, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        q = dense(features=(cfg.num_attention_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(cfg.num_key_value_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(cfg.num_key_value_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        cos, sin = rotary_embedding(positions, head_dim, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        out, pages = paged_attention_core(q, k, v, pages, block_table, start_pos, chunk_lens,
                                          self.page_size, attention_impl=cfg.attention_impl,
                                          sliding_window=cfg.sliding_window)
        out = nn.DenseGeneral(features=cfg.hidden_size,
                              axis=(-2, -1),
                              use_bias=False,
                              dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                              name="o_proj")(out)
        return out, pages


class LlamaBlockCache(nn.Module):
    cfg: LlamaConfig
    page_size: int = 16
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        cfg = self.cfg
        x = carry
        attn_out, layer_pages = LlamaAttentionCache(cfg, self.page_size, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x), positions, layer_pages,
            block_table, start_pos, chunk_lens)
        h = x + attn_out
        out = h + LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="post_attention_layernorm")(h))
        if self.scanned:
            return out, layer_pages
        return out, layer_pages


class LlamaForCausalLMWithCache(nn.Module):
    """Chunked forward with paged KV.  ``apply(variables, tokens, start_pos,
    block_table, cache)`` → (logits, new_cache)."""
    cfg: LlamaConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        embed = nn.Embed(num_embeddings=cfg.vocab_size,
                         features=cfg.hidden_size,
                         dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)

        class _Trunk(nn.Module):
            """Named 'model' to match LlamaForCausalLM's param tree."""
            cfg: LlamaConfig
            page_size: int

            @nn.compact
            def __call__(self, x, cache, positions, block_table, start_pos, chunk_lens):
                if not self.cfg.scan_layers:
                    # unrolled serving trunk (params layout model/layers_i/*,
                    # see unstack_layer_params): straight-line code drops the
                    # scan's while/dynamic-slice bookkeeping — measured ~22ms
                    # of 123ms per 8 fused decode rounds at B32 (r4).  The
                    # cache arrives as a TUPLE of per-layer arenas (donated
                    # leaf-wise); an [L, ...] array would force a whole-arena
                    # dynamic-update per layer
                    new_pages = []
                    for i in range(self.cfg.num_hidden_layers):
                        x, pages_i = LlamaBlockCache(self.cfg, self.page_size,
                                                     name=f"layers_{i}")(
                            x, cache[i], positions, block_table, start_pos, chunk_lens)
                        new_pages.append(pages_i)
                    return x, tuple(new_pages)
                blocks = nn.scan(LlamaBlockCache,
                                 variable_axes={"params": 0},
                                 split_rngs={"params": True},
                                 in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                                 out_axes=0,
                                 length=self.cfg.num_hidden_layers,
                                 metadata_params={nn.PARTITION_NAME: LAYERS})
                x, cache = blocks(self.cfg, self.page_size, scanned=True,
                                  name="layers")(x, cache, positions, block_table, start_pos, chunk_lens)
                return x, cache

        x, cache = _Trunk(cfg, self.page_size, name="model")(x, cache, positions, block_table, start_pos, chunk_lens)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x)
        else:
            logits = nn.DenseGeneral(features=cfg.vocab_size,
                                     use_bias=False,
                                     dtype=cfg.dtype,
                                     param_dtype=cfg.param_dtype,
                                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                     name="lm_head")(x)
        return logits, cache
