"""Llama-family causal LM, TPU-first.

This is the flagship training model (BASELINE.json configs 3–4: Llama-3-8B
ZeRO-3 / Ulysses 32k).  Where the reference injects fused CUDA kernels into a
HF torch module (ref: deepspeed/module_inject/containers/llama.py), we define
the model natively in flax.linen with:

  * ``nn.scan`` over the decoder stack — one compiled layer body, weights get
    a leading ``layers`` axis.  This is what makes ZeRO-3 memory behaviour
    fall out of XLA: sharded weights are all-gathered per scan iteration and
    freed after, the same live-window the reference's param coordinator
    maintains by hand (ref: runtime/zero/partitioned_param_coordinator.py).
  * logical axis names on every param, mapped to mesh axes by the sharding
    rules in ``module_inject/tp_rules.py`` (the AutoTP analog).
  * optional remat (``jax.checkpoint``) per layer — the analog of
    ``runtime/activation_checkpointing/checkpointing.py:948``.
  * a pluggable attention kernel (jnp reference or Pallas flash attention,
    or the Ulysses all-to-all wrapper from ``deepspeed_tpu.sequence``).
"""

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

# Logical axis vocabulary (consumed by module_inject/tp_rules.py)
BATCH = "batch"
SEQ = "seq_len"
from ..axes import EMBED, HEAD_DIM, HEADS, KV_HEADS, LAYERS, MLP, VOCAB  # noqa: F401 (canonical vocabulary)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 8192
    rope_theta: float = 500000.0
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "reference"  # reference | flash | ulysses
    attention_bias: bool = False  # qkv bias (Qwen2-style checkpoints)
    attention_out_bias: bool = False  # o_proj bias (InternLM-1-style checkpoints)
    sliding_window: int = 0  # 0 = full attention; >0 = mistral-style window

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        """Build from a transformers LlamaConfig (duck-typed)."""
        fields = dict(
            vocab_size=hf_cfg.vocab_size,
            hidden_size=hf_cfg.hidden_size,
            intermediate_size=hf_cfg.intermediate_size,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=getattr(hf_cfg, "num_key_value_heads", hf_cfg.num_attention_heads),
            max_position_embeddings=hf_cfg.max_position_embeddings,
            rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
            rms_norm_eps=getattr(hf_cfg, "rms_norm_eps", 1e-5),
            tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", False),
            attention_bias=getattr(hf_cfg, "attention_bias", False),
            # HF gates the window with use_sliding_window (qwen2 ships
            # sliding_window=32768 but use_sliding_window=False)
            sliding_window=((getattr(hf_cfg, "sliding_window", None) or 0)
                            if getattr(hf_cfg, "use_sliding_window", True) else 0),
        )
        # qwen2's max_window_layers keeps the first N layers full-attention;
        # mixed per-layer windows don't fit one scanned layer body
        mwl = getattr(hf_cfg, "max_window_layers", None)
        if fields["sliding_window"] and mwl is not None:
            if mwl >= hf_cfg.num_hidden_layers:
                fields["sliding_window"] = 0      # no layer actually windowed
            elif mwl > 0:
                raise NotImplementedError(
                    f"mixed full/window attention (max_window_layers={mwl} of "
                    f"{hf_cfg.num_hidden_layers}) is unsupported with scan-over-layers")
        fields.update(overrides)
        return LlamaConfig(**fields)


PRESETS = {
    "llama3-8b": LlamaConfig(vocab_size=128256, hidden_size=4096, intermediate_size=14336, num_hidden_layers=32,
                             num_attention_heads=32, num_key_value_heads=8),
    # the reference FastGen headline model (blogs/deepspeed-fastgen: Llama-2-70B
    # served TP-sharded over 4 GPUs)
    "llama2-70b": LlamaConfig(vocab_size=32000, hidden_size=8192, intermediate_size=28672,
                              num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8,
                              rope_theta=10000.0),
    "llama2-7b": LlamaConfig(vocab_size=32000, hidden_size=4096, intermediate_size=11008, num_hidden_layers=32,
                             num_attention_heads=32, num_key_value_heads=32, rope_theta=10000.0),
    "tiny": LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                        num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=256,
                        rope_theta=10000.0),
    "125m": LlamaConfig(vocab_size=32000, hidden_size=768, intermediate_size=2048, num_hidden_layers=12,
                        num_attention_heads=12, num_key_value_heads=12, rope_theta=10000.0),
}


def _logical(init, names):
    return nn.with_logical_partitioning(init, names)


def _in_manual_mesh() -> bool:
    """True inside a shard_map body (e.g. the pipeline rotation): GSPMD-level
    sharding constraints are meaningless/illegal there."""
    from ..comm.mesh import in_manual_mesh
    return in_manual_mesh()


def _skip_constraint(x) -> bool:
    """Constraints are trace-time directives to GSPMD; eager values (golden
    tests calling attention outside jit) and shard_map bodies skip them."""
    return not isinstance(x, jax.core.Tracer) or _in_manual_mesh()


def _resolve_remat_policy(name: str):
    """jax.checkpoint_policies lookup plus 'flash_saveable': projection
    dots AND the flash kernel's tagged outputs (out + lse) are saved, so
    the backward runs the dedicated dq/dkv kernels against saved residuals
    instead of re-running the forward kernel first."""
    if name == "flash_saveable":
        return jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("flash_out", "flash_lse"))
    if name == "flash_only":
        # memory-lean large-model policy: ONLY the flash kernel outputs are
        # saved (so the backward still runs the dedicated dq/dkv kernels, no
        # third attention pass) while every projection/MLP dot recomputes —
        # under scan-over-layers the residual stack stays O(layers·B·S·E)
        # instead of O(layers·B·S·intermediate)
        return jax.checkpoint_policies.save_only_these_names("flash_out", "flash_lse")
    return getattr(jax.checkpoint_policies, name, None)


def activation_constraint(x):
    """Pin a [B, S, E] activation to the canonical (data×expert, seq, -)
    layout.  Without this, sharding propagation lets the embedding lookup
    inherit the table's ZeRO-3 fsdp sharding on the E dim, and the scan
    carry (B,S layout) then needs an SPMD "involuntary full
    rematerialization" reshard on while entry/exit — replicate + repartition
    of the whole residual stream, once forward and once backward."""
    from ..comm.mesh import BATCH_AXES, SEQ_AXIS, get_global_mesh, has_global_mesh
    if not has_global_mesh() or _skip_constraint(x):
        return x
    mesh = get_global_mesh()
    if all(mesh.shape.get(a, 1) == 1 for a in (*BATCH_AXES, SEQ_AXIS)):
        return x
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(BATCH_AXES, SEQ_AXIS if mesh.shape.get(SEQ_AXIS, 1) > 1 else None, None)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def logits_constraint(logits):
    """Pin [B, S, V] logits to (data×expert, seq, tensor): with the lm_head
    kernel vocab-parallel (see tp_rules.vocab_rules) this keeps the matmul's
    fsdp all-gather on the weight side and the loss vocab-sharded over tp."""
    from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, get_global_mesh, has_global_mesh
    if not has_global_mesh() or _skip_constraint(logits):
        return logits
    mesh = get_global_mesh()
    if all(mesh.shape.get(a, 1) == 1 for a in (*BATCH_AXES, SEQ_AXIS, TENSOR_AXIS)):
        return logits
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(BATCH_AXES,
                         SEQ_AXIS if mesh.shape.get(SEQ_AXIS, 1) > 1 else None,
                         TENSOR_AXIS if mesh.shape.get(TENSOR_AXIS, 1) > 1 else None)
    return jax.lax.with_sharding_constraint(logits, NamedSharding(mesh, spec))


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("weight", _logical(nn.initializers.ones_init(), (EMBED, )), (x.shape[-1], ),
                           self.param_dtype)
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        normed = x32 * jax.lax.rsqrt(var + self.eps)
        return (normed * scale.astype(jnp.float32)).astype(self.dtype)


def rotary_embedding(positions, head_dim, theta):
    """RoPE tables; fp32 for precision (ref kernel: csrc/transformer/inference
    rotary — here a pure-jnp pair that XLA fuses into the attention matmuls)."""
    inv_freq = 1.0 / (theta**(jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq  # [B, S, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin):
    # x: [B, S, N, D]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _attn_logits_constraint(t):
    """Pin [B, N, Q, K] attention scores (and everything softmax derives from
    them) to the head-sharded layout the Ulysses all-to-all establishes.
    Without it, the backward recompute under jax.checkpoint resolves parts of
    the softmax head-sharded (from q/k) and parts seq-sharded (from the
    positions/mask side), and the partitioner falls back to involuntary full
    rematerialization between them."""
    from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS, get_global_mesh, has_global_mesh
    if not has_global_mesh() or _skip_constraint(t):
        return t
    mesh = get_global_mesh()
    head_axes = tuple(a for a in (SEQ_AXIS, TENSOR_AXIS) if mesh.shape.get(a, 1) > 1)
    if not head_axes and all(mesh.shape.get(a, 1) == 1 for a in BATCH_AXES):
        return t
    from jax.sharding import NamedSharding, PartitionSpec
    spec = PartitionSpec(BATCH_AXES, head_axes or None, None, None)
    return jax.lax.with_sharding_constraint(t, NamedSharding(mesh, spec))


def reference_attention(q, k, v, *, causal=True, segment_ids=None, sliding_window=0,
                        attn_bias=None):
    """Pure-jnp softmax attention (the golden path; swapped for the Pallas
    flash kernel via config.attention_impl).  ``sliding_window>0`` restricts
    each query to the last W keys (mistral).  ``attn_bias`` is an additive
    pre-softmax bias broadcastable to [B, N, Sq, Sk] (alibi slopes)."""
    b, sq, nh, hd = q.shape
    _, sk, nkv, _ = k.shape
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    logits = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if attn_bias is not None:
        logits = logits + attn_bias.astype(jnp.float32)
    logits = _attn_logits_constraint(logits)
    if causal:
        qpos = jnp.arange(sq)[:, None]
        kpos = jnp.arange(sk)[None, :]
        mask = qpos >= kpos
        if sliding_window and sliding_window > 0:
            mask = mask & (kpos > qpos - sliding_window)
        logits = jnp.where(mask[None, None], logits, -1e30)
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        logits = jnp.where(seg_mask[:, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnqk,bknd->bqnd", probs.astype(v.dtype), v)


def chunked_attention(q, k, v, *, causal=True, segment_ids=None, sliding_window=0,
                      chunk_size=256, unroll_chunks=16):
    """Query-chunked attention with the softmax over the full key axis per
    chunk — never materializes the [B, N, S, S] score tensor that makes
    ``reference_attention`` HBM-bound at training sizes (each chunk's scores
    are [B, N, C, S] and die inside the scan iteration).  The online-softmax
    variant for host-offloaded KV lives in sequence/fpdt_layer.py; this one
    assumes K/V fit on-chip, which holds whenever the model itself does.
    ref role: csrc/transformer softmax/attention fusion — the memory shape of
    FlashAttention without the Pallas kernel.

    Short sequences (≤ ``unroll_chunks`` chunks) take an *unrolled* python
    loop with static per-chunk causal key ranges instead of ``lax.scan``:
    (a) chunk i only reads keys [0, (i+1)·C) — the scan path computes full
    [C, S] scores and masks, 2× the causal FLOPs; (b) XLA's scan VJP stacks
    residuals with dynamic_update_slice and differentiates through dynamic
    slices, which profiled HBM-bound at 19–32 TFLOP/s (~40 ms/step at bench
    size) — unrolled chunks autodiff into clean static-shape dots that run
    at MXU speed.  Long sequences keep the scan (compile-size bound)."""
    b, sq, nh, hd = q.shape
    _, sk, nkv, _ = k.shape
    if nkv != nh:
        rep = nh // nkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    if sq % chunk_size != 0 or sq < chunk_size:
        from ..utils.logging import logger
        logger.warning(f"chunked_attention: seq {sq} not a multiple of chunk {chunk_size}; "
                       "falling back to reference attention (full [B,N,S,S] scores)")
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                   sliding_window=sliding_window)
    scale = 1.0 / jnp.sqrt(hd).astype(jnp.float32)
    nc = sq // chunk_size
    kpos_full = jnp.arange(sk)

    if nc <= unroll_chunks and sq == sk:
        outs = []
        for i in range(nc):
            q_i = jax.lax.slice_in_dim(q, i * chunk_size, (i + 1) * chunk_size, axis=1)
            kend = (i + 1) * chunk_size if causal else sk
            kstart = 0
            if causal and sliding_window and sliding_window > 0:
                # earliest key visible to this chunk, rounded down to a lane-
                # friendly multiple so the slice stays tiled
                kstart = max(0, ((i * chunk_size - sliding_window + 1) // 128) * 128)
            k_i = jax.lax.slice_in_dim(k, kstart, kend, axis=1)
            v_i = jax.lax.slice_in_dim(v, kstart, kend, axis=1)
            s = jnp.einsum("bcnd,bknd->bnck", q_i, k_i,
                           preferred_element_type=jnp.float32) * scale
            qpos = i * chunk_size + jnp.arange(chunk_size)[:, None]
            kpos = kstart + jnp.arange(kend - kstart)[None, :]
            if causal:
                mask = qpos >= kpos
                if sliding_window and sliding_window > 0:
                    mask = mask & (kpos > qpos - sliding_window)
                s = jnp.where(mask[None, None], s, -1e30)
            if segment_ids is not None:
                q_seg = jax.lax.slice_in_dim(segment_ids, i * chunk_size, (i + 1) * chunk_size, axis=1)
                k_seg = jax.lax.slice_in_dim(segment_ids, kstart, kend, axis=1)
                s = jnp.where((q_seg[:, :, None] == k_seg[:, None, :])[:, None], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            outs.append(jnp.einsum("bnck,bknd->bcnd", p.astype(v.dtype), v_i))
        return jnp.concatenate(outs, axis=1)

    qc = q.reshape(b, nc, chunk_size, nh, hd).transpose(1, 0, 2, 3, 4)  # [nc,B,C,N,D]

    def body(carry, args):
        q_i, i = args
        # [B,N,C,S] f32 scores for this query chunk only
        s = jnp.einsum("bcnd,bknd->bnck", q_i, k,
                       preferred_element_type=jnp.float32) * scale
        qpos = i * chunk_size + jnp.arange(chunk_size)
        mask = jnp.ones((chunk_size, sk), bool)
        if causal:
            mask = qpos[:, None] >= kpos_full[None, :]
            if sliding_window and sliding_window > 0:
                mask = mask & (kpos_full[None, :] > qpos[:, None] - sliding_window)
        s = jnp.where(mask[None, None], s, -1e30)
        if segment_ids is not None:
            q_seg = jax.lax.dynamic_slice_in_dim(segment_ids, i * chunk_size, chunk_size, axis=1)
            seg_mask = q_seg[:, :, None] == segment_ids[:, None, :]
            s = jnp.where(seg_mask[:, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bnck,bknd->bcnd", p.astype(v.dtype), v)
        return carry, o

    # segment_ids prevents the static mask slice above from being traced with
    # a dynamic start when unused; keep i traced for the dynamic path
    _, out = jax.lax.scan(body, (), (qc, jnp.arange(nc)))
    return out.transpose(1, 0, 2, 3, 4).reshape(b, sq, nh, hd)


def get_attention_impl(name: str) -> Callable:
    if name == "reference":
        return reference_attention
    if name == "chunked":
        return chunked_attention
    if name == "flash":
        from ..ops.flash_attention import flash_attention
        return flash_attention
    if name == "ulysses":
        from ..sequence.layer import DistributedAttention
        return DistributedAttention(reference_attention)
    if name == "fpdt":
        from ..sequence.fpdt_layer import FPDTAttention
        return FPDTAttention(ulysses=False)
    if name == "ring":
        from ..sequence.ring import ring_attention
        return ring_attention
    raise ValueError(f"Unknown attention impl {name}")


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dense = partial(nn.DenseGeneral, use_bias=cfg.attention_bias, dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype)
        q = dense(features=(cfg.num_attention_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(cfg.num_key_value_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(cfg.num_key_value_heads, head_dim),
                  kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        cos, sin = rotary_embedding(positions, head_dim, cfg.rope_theta)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if cfg.sliding_window and cfg.attention_impl not in ("reference", "chunked", "flash"):
            raise NotImplementedError("sliding_window supports attention_impl reference/chunked/flash "
                                      "(ulysses/ring window masks land with those kernels)")
        attn_fn = get_attention_impl(cfg.attention_impl)
        kw = {"sliding_window": cfg.sliding_window} if cfg.sliding_window else {}
        out = attn_fn(q, k, v, causal=True, segment_ids=segment_ids, **kw)
        out = nn.DenseGeneral(features=cfg.hidden_size,
                              axis=(-2, -1),
                              use_bias=cfg.attention_out_bias,
                              dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype,
                              kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                              name="o_proj")(out)
        return out


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        gate = dense(features=cfg.intermediate_size,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)),
                     name="gate_proj")(x)
        up = dense(features=cfg.intermediate_size,
                   kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)),
                   name="up_proj")(x)
        h = nn.silu(gate) * up
        return dense(features=cfg.hidden_size,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)),
                     name="down_proj")(h)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, pld_scale=None):
        cfg = self.cfg
        # pins the scan carry to (data×expert, seq, -) in BOTH directions:
        # the transpose of a constraint on the block input constrains the
        # backward carry (dx), which sharding propagation would otherwise
        # solve to E-sharded from the fsdp-sharded kernels, forcing an
        # involuntary full-remat reshard at the while boundary
        x = activation_constraint(x)
        # progressive layer drop: the whole block's residual contribution is
        # gated by pld_scale = keep_mask/keep_prob (ref: PLD paper eq. 6 and
        # runtime/progressive_layer_drop.py pld_layer_mask)
        s = 1.0 if pld_scale is None else pld_scale.astype(cfg.dtype)
        h = x + s * LlamaAttention(cfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x), positions, segment_ids)
        out = h + s * LlamaMLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="post_attention_layernorm")(h))
        if self.scanned:
            return out, None
        return out


class ScannedBlocks(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, segment_ids=None, pld_scale=None):
        cfg = self.cfg
        block_cls = LlamaBlock
        if cfg.remat:
            policy = _resolve_remat_policy(cfg.remat_policy)
            block_cls = nn.remat(LlamaBlock, policy=policy, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls,
                             variable_axes={"params": 0},
                             split_rngs={"params": True},
                             in_axes=(nn.broadcast, nn.broadcast, 0),
                             length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            if pld_scale is None:
                pld_scale = jnp.ones((cfg.num_hidden_layers, ), jnp.float32)
            x, _ = blocks(cfg, scanned=True, name="layers")(x, positions, segment_ids, pld_scale)
            return x
        for i in range(cfg.num_hidden_layers):
            s_i = None if pld_scale is None else pld_scale[i]
            x = block_cls(cfg, name=f"layers_{i}")(x, positions, segment_ids, s_i)
        return x


class LlamaForCausalLM(nn.Module):
    cfg: LlamaConfig
    supports_pld = True  # engine passes pld_scale when PLD is configured

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None, pld_scale=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        embed = nn.Embed(num_embeddings=cfg.vocab_size,
                         features=cfg.hidden_size,
                         dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = activation_constraint(embed(input_ids))
        x = ScannedBlocks(cfg, name="model")(x, positions, segment_ids, pld_scale)
        x = activation_constraint(x)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        if cfg.tie_word_embeddings:
            logits = embed.attend(x)
        else:
            logits = nn.DenseGeneral(features=cfg.vocab_size,
                                     use_bias=False,
                                     dtype=cfg.dtype,
                                     param_dtype=cfg.param_dtype,
                                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                     name="lm_head")(x)
        return logits_constraint(logits)


@jax.custom_vjp
def causal_lm_loss(logits, labels, loss_mask=None):
    """Token-mean cross entropy in fp32 (ref: sequence/cross_entropy.py's
    vocab-parallel CE is realised by GSPMD when lm_head is vocab-sharded).

    Computed as logsumexp(logits) - logits[label] rather than through
    log_softmax: the reductions stream over the vocab axis (XLA fuses the
    f32 cast into them), so no [B, S, V] f32 log-prob tensor is ever
    materialized — at bench size that tensor alone is 1 GB/step of HBM
    traffic.  The hand-written VJP emits dlogits = (softmax − onehot)·w
    directly in the logits dtype as one elementwise fusion over the saved
    bf16 logits; XLA's autodiff instead materializes the f32 softmax and
    converts it (profiled ~4 ms/step HBM-bound at bench size)."""
    loss, _ = _causal_lm_loss_fwd(logits, labels, loss_mask)
    return loss


def _causal_lm_loss_fwd(logits, labels, loss_mask):
    lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)  # [B, S]
    tgt = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0].astype(jnp.float32)
    nll = lse - tgt
    if loss_mask is not None:
        denom = jnp.maximum(loss_mask.sum(), 1.0)
        loss = (nll * loss_mask).sum() / denom
    else:
        denom = jnp.float32(nll.size)
        loss = nll.mean()
    return loss, (logits, labels, loss_mask, lse, denom)


def _causal_lm_loss_bwd(res, g):
    logits, labels, loss_mask, lse, denom = res
    w = g / denom
    if loss_mask is not None:
        w = w * loss_mask  # [B, S]
    else:
        w = jnp.broadcast_to(w, lse.shape)
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = labels[..., None] == jnp.arange(logits.shape[-1])[None, None, :]
    dlogits = ((p - onehot) * w[..., None]).astype(logits.dtype)
    return dlogits, None, None


causal_lm_loss.defvjp(_causal_lm_loss_fwd, _causal_lm_loss_bwd)


# --------------------------------------------------------------------------
# Pipeline-parallel building blocks (consumed by runtime/pipe/module.py).
# The reference expresses pipelined GPT models as a flat LayerSpec list
# (embed → N×block → norm → head); these are the Llama equivalents.  The
# block derives positions from the sequence length so the residual stream
# is the only tensor travelling through the pipeline rotation.


class LlamaEmbedLayer(nn.Module):
    cfg: LlamaConfig

    def setup(self):
        cfg = self.cfg
        self.embed_tokens = nn.Embed(num_embeddings=cfg.vocab_size,
                                     features=cfg.hidden_size,
                                     dtype=cfg.dtype,
                                     param_dtype=cfg.param_dtype,
                                     embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)))

    def __call__(self, input_ids):
        return self.embed_tokens(input_ids)

    def attend(self, x):
        """Tied LM head: logits via the embedding matrix (used by the
        pipeline's TiedLayerSpec forward_fn when tie_word_embeddings)."""
        return self.embed_tokens.attend(x)


class LlamaPipeBlock(nn.Module):
    """One decoder block with self-derived positions (pipeline body)."""
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        positions = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        return LlamaBlock(self.cfg, name="block")(x, positions)


class LlamaHeadLayer(nn.Module):
    """Final norm + LM head (last pipeline stage tail)."""
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        return nn.DenseGeneral(features=cfg.vocab_size,
                               use_bias=False,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                               name="lm_head")(x)


class LlamaNormLayer(nn.Module):
    """Final norm alone (last-stage tail when the LM head is tied)."""
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        return RMSNorm(self.cfg.rms_norm_eps, self.cfg.dtype, self.cfg.param_dtype, name="norm")(x)


def llama_pipeline_layers(cfg: LlamaConfig):
    """Flat layer list for PipelineModule (ref: the GPT2ModelPipe pattern in
    DeepSpeed examples built on pipe/module.py LayerSpec).  With
    ``tie_word_embeddings`` the head reuses the embedding matrix via
    TiedLayerSpec (ref: pipe/module.py TiedLayerSpec), matching
    LlamaForCausalLM's ``embed.attend`` path."""
    from ..runtime.pipe.module import LayerSpec, TiedLayerSpec
    blocks = [LayerSpec(LlamaPipeBlock, cfg) for _ in range(cfg.num_hidden_layers)]
    if cfg.tie_word_embeddings:
        embed = TiedLayerSpec("embed", LlamaEmbedLayer, cfg)
        head = TiedLayerSpec("embed", LlamaEmbedLayer, cfg,
                             forward_fn=lambda mod, variables, x: mod.apply(variables, x, method="attend"))
        return [embed] + blocks + [LayerSpec(LlamaNormLayer, cfg), head]
    return ([LayerSpec(LlamaEmbedLayer, cfg)] + blocks + [LayerSpec(LlamaHeadLayer, cfg)])
