"""OPT family — decoder-only with learned positions and ReLU MLP.

ref: deepspeed/inference/v2/model_implementations/opt/ (+ module_inject
containers/opt.py) — the reference serves OPT through its kernel containers;
here it is a first-class flax model sharing the logical-axis vocabulary of
models/llama.py so every parallelism axis (ZeRO/TP/SP) applies unchanged.

Architecture (HF OPTForCausalLM): token embed + learned position embed
(offset 2), pre-LN decoder blocks (LayerNorm with bias), standard MHA with
qkv+out biases, ReLU MLP (fc1/fc2 with bias), final LN, tied or separate
lm head.
"""

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from .llama import EMBED, HEADS, HEAD_DIM, KV_HEADS, LAYERS, MLP, VOCAB, _logical, get_attention_impl


@dataclass(frozen=True)
class OPTConfig:
    vocab_size: int = 50272
    hidden_size: int = 768
    ffn_dim: int = 3072
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    max_position_embeddings: int = 2048
    do_layer_norm_before: bool = True
    word_embed_proj_dim: int = 0  # 0 -> hidden_size; opt-350m projects 512->1024
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        proj = getattr(hf_cfg, "word_embed_proj_dim", None)
        fields = dict(vocab_size=hf_cfg.vocab_size,
                      hidden_size=hf_cfg.hidden_size,
                      ffn_dim=hf_cfg.ffn_dim,
                      num_hidden_layers=hf_cfg.num_hidden_layers,
                      num_attention_heads=hf_cfg.num_attention_heads,
                      max_position_embeddings=hf_cfg.max_position_embeddings,
                      do_layer_norm_before=getattr(hf_cfg, "do_layer_norm_before", True),
                      word_embed_proj_dim=0 if proj in (None, hf_cfg.hidden_size) else proj,
                      tie_word_embeddings=getattr(hf_cfg, "tie_word_embeddings", True))
        fields.update(overrides)
        return OPTConfig(**fields)


class OPTAttention(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, x, segment_ids=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        attn_fn = get_attention_impl(cfg.attention_impl)
        out = attn_fn(q, k, v, causal=True, segment_ids=segment_ids)
        return nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                               name="out_proj")(out)


class OPTBlock(nn.Module):
    cfg: OPTConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, segment_ids=None):
        cfg = self.cfg
        ln = partial(nn.LayerNorm, epsilon=1e-5, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        h = x
        a_in = ln(name="self_attn_layer_norm")(h) if cfg.do_layer_norm_before else h
        a = OPTAttention(cfg, name="self_attn")(a_in, segment_ids)
        h = h + a
        if not cfg.do_layer_norm_before:
            h = ln(name="self_attn_layer_norm")(h)
        m_in = ln(name="final_layer_norm")(h) if cfg.do_layer_norm_before else h
        m = nn.Dense(cfg.ffn_dim, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)), name="fc1")(m_in)
        m = jax.nn.relu(m)
        m = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)), name="fc2")(m)
        out = h + m
        if not cfg.do_layer_norm_before:
            out = ln(name="final_layer_norm")(out)
        if self.scanned:
            return out, None
        return out


class OPTForCausalLM(nn.Module):
    cfg: OPTConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        B, S = input_ids.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        proj_dim = cfg.word_embed_proj_dim or cfg.hidden_size
        embed = nn.Embed(cfg.vocab_size, proj_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        # HF OPT offsets learned positions by 2 (padding convention)
        pos_embed = nn.Embed(cfg.max_position_embeddings + 2, cfg.hidden_size, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype,
                             embedding_init=nn.initializers.normal(0.02),
                             name="embed_positions")
        x = embed(input_ids)
        if proj_dim != cfg.hidden_size:  # opt-350m: project_in/out around the stack
            x = nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_in")(x)
        x = x + pos_embed(positions + 2)

        block_cls = OPTBlock
        if cfg.remat:
            block_cls = nn.remat(OPTBlock, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls, variable_axes={"params": 0}, split_rngs={"params": True},
                             in_axes=(nn.broadcast, ), length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = blocks(cfg, scanned=True, name="layers")(x, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"layers_{i}")(x, segment_ids)

        if cfg.do_layer_norm_before:  # HF: final LN exists only for pre-LN OPT
            x = nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             name="final_layer_norm")(x)
        if proj_dim != cfg.hidden_size:
            x = nn.Dense(proj_dim, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_out")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x)
        return nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                               name="lm_head")(x)
