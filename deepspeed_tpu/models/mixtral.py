"""Mixtral-style MoE causal LM (Llama attention + sparse-MoE FFN), TPU-first.

Reference coverage: the MoE training path — ``deepspeed/moe/layer.py`` MoE
wired into a GPT stack (Megatron-DeepSpeed MoE models; BASELINE.json config
5: Mixtral-8x7B EP) and the v2 inference implementation
``inference/v2/model_implementations/mixtral``.  The block swaps the dense
SwiGLU MLP for ``deepspeed_tpu.moe.MoE`` (top-k gating → expert-axis
all-to-all → expert FFN bank → combine) and threads the auxiliary
load-balancing loss through the layer scan, matching the reference's
contract where the MoE layer returns (out, l_aux, exp_counts) and the user
adds ``l_aux`` to the loss.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..moe.layer import MoE
from .llama import (EMBED, LAYERS, VOCAB, LlamaAttention, LlamaConfig, RMSNorm, _logical, causal_lm_loss)


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    max_position_embeddings: int = 32768
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    num_local_experts: int = 8
    num_experts_per_tok: int = 2
    router_aux_loss_coef: float = 0.02
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "reference"

    def as_llama(self) -> LlamaConfig:
        return LlamaConfig(vocab_size=self.vocab_size,
                           hidden_size=self.hidden_size,
                           intermediate_size=self.intermediate_size,
                           num_hidden_layers=self.num_hidden_layers,
                           num_attention_heads=self.num_attention_heads,
                           num_key_value_heads=self.num_key_value_heads,
                           max_position_embeddings=self.max_position_embeddings,
                           rope_theta=self.rope_theta,
                           rms_norm_eps=self.rms_norm_eps,
                           dtype=self.dtype,
                           param_dtype=self.param_dtype,
                           attention_impl=self.attention_impl)

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(
            vocab_size=hf_cfg.vocab_size,
            hidden_size=hf_cfg.hidden_size,
            intermediate_size=hf_cfg.intermediate_size,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            num_key_value_heads=getattr(hf_cfg, "num_key_value_heads", 8),
            max_position_embeddings=hf_cfg.max_position_embeddings,
            rope_theta=getattr(hf_cfg, "rope_theta", 1e6),
            num_local_experts=getattr(hf_cfg, "num_local_experts", 8),
            num_experts_per_tok=getattr(hf_cfg, "num_experts_per_tok", 2),
            router_aux_loss_coef=getattr(hf_cfg, "router_aux_loss_coef", 0.02),
        )
        fields.update(overrides)
        return MixtralConfig(**fields)


PRESETS = {
    "mixtral-8x7b": MixtralConfig(),
    "tiny": MixtralConfig(vocab_size=128, hidden_size=64, intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2, max_position_embeddings=64,
                          rope_theta=1e4, num_local_experts=4, num_experts_per_tok=2),
}


class MixtralBlock(nn.Module):
    cfg: MixtralConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, positions, segment_ids=None):
        cfg = self.cfg
        x, l_aux_acc = carry if self.scanned else (carry, jnp.zeros((), jnp.float32))
        lcfg = cfg.as_llama()
        h = x + LlamaAttention(lcfg, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x), positions, segment_ids)
        moe_out, l_aux, _counts = MoE(hidden_size=cfg.hidden_size,
                                      num_experts=cfg.num_local_experts,
                                      intermediate_size=cfg.intermediate_size,
                                      k=cfg.num_experts_per_tok,
                                      capacity_factor=cfg.capacity_factor,
                                      eval_capacity_factor=cfg.eval_capacity_factor,
                                      min_capacity=cfg.min_capacity,
                                      drop_tokens=cfg.drop_tokens,
                                      dtype=cfg.dtype,
                                      param_dtype=cfg.param_dtype,
                                      name="block_sparse_moe")(
                                          RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype,
                                                  name="post_attention_layernorm")(h))
        out = h + moe_out
        l_aux_acc = l_aux_acc + l_aux.astype(jnp.float32)
        if self.scanned:
            return (out, l_aux_acc), None
        return out, l_aux_acc


class MixtralForCausalLM(nn.Module):
    """Returns ``(logits, l_aux_total)`` — pair the engine with
    ``mixtral_lm_loss``."""
    cfg: MixtralConfig

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        embed = nn.Embed(num_embeddings=cfg.vocab_size,
                         features=cfg.hidden_size,
                         dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        l_aux = jnp.zeros((), jnp.float32)

        block_cls = MixtralBlock
        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block_cls = nn.remat(MixtralBlock, policy=policy, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls,
                             variable_axes={"params": 0},
                             split_rngs={"params": True},
                             in_axes=(nn.broadcast, nn.broadcast),
                             length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            (x, l_aux), _ = blocks(cfg, scanned=True, name="layers")((x, l_aux), positions, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x, l_aux_i = block_cls(cfg, name=f"layers_{i}")(x, positions, segment_ids)
                l_aux = l_aux + l_aux_i

        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        logits = nn.DenseGeneral(features=cfg.vocab_size,
                                 use_bias=False,
                                 dtype=cfg.dtype,
                                 param_dtype=cfg.param_dtype,
                                 kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                 name="lm_head")(x)
        return logits, l_aux


def mixtral_lm_loss(outputs, labels, loss_mask=None, aux_loss_coef=0.02):
    """CE + router aux loss (ref: the user-side ``loss += l_aux * coef``
    contract of deepspeed/moe/layer.py)."""
    logits, l_aux = outputs
    return causal_lm_loss(logits, labels, loss_mask) + aux_loss_coef * l_aux


def make_mixtral_loss_fn(cfg: MixtralConfig):
    def loss_fn(outputs, batch):
        return mixtral_lm_loss(outputs, batch["labels"], batch.get("loss_mask"), cfg.router_aux_loss_coef)

    return loss_fn
