"""GPT-2 family causal LM, TPU-first.

Reference coverage: the reference ships GPT-2 as an inference injection
policy (``deepspeed/module_inject/containers/gpt2.py``, HFGPT2LayerPolicy)
and as the Megatron_GPT2 integration test family (``tests/model/
Megatron_GPT2``).  Here it is a native flax model sharing the Llama stack's
design: scan-over-layers, logical-axis params (module_inject/tp_rules.py),
per-layer remat, pluggable attention.

Architecture notes (GPT-2 vs Llama): learned absolute position embeddings,
pre-LN with bias, GELU MLP (4×), fused-qkv-style biases, tied LM head.
"""

import dataclasses
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import (EMBED, HEAD_DIM, HEADS, LAYERS, MLP, VOCAB, _logical, causal_lm_loss, get_attention_impl)

POSITIONS = "positions"  # learned position table axis (replicated)


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    layer_norm_epsilon: float = 1e-5
    tie_word_embeddings: bool = True
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"
    attention_impl: str = "reference"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(
            vocab_size=hf_cfg.vocab_size,
            n_positions=getattr(hf_cfg, "n_positions", 1024),
            hidden_size=getattr(hf_cfg, "n_embd", getattr(hf_cfg, "hidden_size", 768)),
            num_hidden_layers=getattr(hf_cfg, "n_layer", getattr(hf_cfg, "num_hidden_layers", 12)),
            num_attention_heads=getattr(hf_cfg, "n_head", getattr(hf_cfg, "num_attention_heads", 12)),
            layer_norm_epsilon=getattr(hf_cfg, "layer_norm_epsilon", 1e-5),
        )
        fields.update(overrides)
        return GPT2Config(**fields)


PRESETS = {
    "gpt2-125m": GPT2Config(),
    "gpt2-medium": GPT2Config(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16),
    "gpt2-large": GPT2Config(hidden_size=1280, num_hidden_layers=36, num_attention_heads=20),
    "gpt2-xl": GPT2Config(hidden_size=1600, num_hidden_layers=48, num_attention_heads=25),
    "tiny": GPT2Config(vocab_size=128, n_positions=64, hidden_size=64, num_hidden_layers=2,
                       num_attention_heads=4),
}


class GPT2Attention(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x, segment_ids=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        qkv = dense(features=(3, cfg.num_attention_heads, head_dim),
                    kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, None, HEADS, HEAD_DIM)),
                    bias_init=_logical(nn.initializers.zeros_init(), (None, HEADS, HEAD_DIM)),
                    name="c_attn")(x)
        q, k, v = qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
        attn_fn = get_attention_impl(cfg.attention_impl)
        out = attn_fn(q, k, v, causal=True, segment_ids=segment_ids)
        return nn.DenseGeneral(features=cfg.hidden_size,
                               axis=(-2, -1),
                               use_bias=True,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
                               bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                               name="c_proj")(out)


class GPT2MLP(nn.Module):
    cfg: GPT2Config

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.DenseGeneral(features=4 * cfg.hidden_size,
                            use_bias=True,
                            dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, MLP)),
                            bias_init=_logical(nn.initializers.zeros_init(), (MLP, )),
                            name="c_fc")(x)
        h = nn.gelu(h, approximate=True)
        return nn.DenseGeneral(features=cfg.hidden_size,
                               use_bias=True,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (MLP, EMBED)),
                               bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                               name="c_proj")(h)


class GPT2Block(nn.Module):
    cfg: GPT2Config
    scanned: bool = False

    @nn.compact
    def __call__(self, x, segment_ids=None):
        cfg = self.cfg
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     scale_init=_logical(nn.initializers.ones_init(), (EMBED, )),
                     bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )))
        h = x + GPT2Attention(cfg, name="attn")(ln(name="ln_1")(x), segment_ids)
        out = h + GPT2MLP(cfg, name="mlp")(ln(name="ln_2")(h))
        if self.scanned:
            return out, None
        return out


class GPT2LMHeadModel(nn.Module):
    """GPT-2 causal LM (``transformers.GPT2LMHeadModel`` surface)."""
    cfg: GPT2Config

    @nn.compact
    def __call__(self, input_ids, positions=None, segment_ids=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        wte = nn.Embed(num_embeddings=cfg.vocab_size,
                       features=cfg.hidden_size,
                       dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype,
                       embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                       name="wte")
        wpe = nn.Embed(num_embeddings=cfg.n_positions,
                       features=cfg.hidden_size,
                       dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype,
                       embedding_init=_logical(nn.initializers.normal(0.01), (POSITIONS, EMBED)),
                       name="wpe")
        x = wte(input_ids) + wpe(positions)

        block_cls = GPT2Block
        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            block_cls = nn.remat(GPT2Block, policy=policy, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            blocks = nn.scan(block_cls,
                             variable_axes={"params": 0},
                             split_rngs={"params": True},
                             in_axes=(nn.broadcast, ),
                             length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = blocks(cfg, scanned=True, name="h")(x, segment_ids)
        else:
            for i in range(cfg.num_hidden_layers):
                x = block_cls(cfg, name=f"h_{i}")(x, segment_ids)

        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         scale_init=_logical(nn.initializers.ones_init(), (EMBED, )),
                         bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                         name="ln_f")(x)
        if cfg.tie_word_embeddings:
            return wte.attend(x)
        return nn.DenseGeneral(features=cfg.vocab_size,
                               use_bias=False,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, VOCAB)),
                               name="lm_head")(x)


gpt2_lm_loss = causal_lm_loss
