"""CLIP — dual-encoder text/vision model (stable-diffusion's conditioning
encoder and the reference's CLIP injection target).

ref: deepspeed/module_inject/containers/clip.py (HFCLIPLayerPolicy) — the
reference TP-injects the CLIP encoder layers inside diffusion pipelines;
here the whole model is a flax module pair (pre-LN transformer towers,
quick-GELU MLPs, causal text attention with EOS pooling, patch-conv vision
embeddings) fed by a weight-conversion policy
(inference/v2/model_implementations/policies.ClipPolicy), so text/vision
encoders serve through the same jitted v1 path as every other family.
"""

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import EMBED, HEAD_DIM, HEADS, MLP, VOCAB, _logical


@dataclasses.dataclass(frozen=True)
class ClipTextConfig:
    vocab_size: int = 49408
    hidden_size: int = 512
    num_hidden_layers: int = 12
    num_attention_heads: int = 8
    intermediate_size: int = 2048
    max_position_embeddings: int = 77
    layer_norm_eps: float = 1e-5
    eos_token_id: int = 49407
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


@dataclasses.dataclass(frozen=True)
class ClipVisionConfig:
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    image_size: int = 224
    patch_size: int = 32
    num_channels: int = 3
    layer_norm_eps: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32


def quick_gelu(x):
    return x * jax.nn.sigmoid(1.702 * x)


class ClipAttention(nn.Module):
    hidden_size: int
    num_heads: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, causal: bool):
        H = self.num_heads
        D = self.hidden_size // H
        dense = lambda feats, names, name: nn.DenseGeneral(
            feats, use_bias=True, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_logical(nn.initializers.lecun_normal(), names), name=name)
        q = dense((H, D), (EMBED, HEADS, HEAD_DIM), "q_proj")(x)
        k = dense((H, D), (EMBED, HEADS, HEAD_DIM), "k_proj")(x)
        v = dense((H, D), (EMBED, HEADS, HEAD_DIM), "v_proj")(x)
        scale = 1.0 / jnp.sqrt(D).astype(jnp.float32)
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
        if causal:
            S = x.shape[1]
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
        return nn.DenseGeneral(self.hidden_size, axis=(-2, -1), use_bias=True,
                               dtype=self.dtype, param_dtype=self.param_dtype,
                               kernel_init=_logical(nn.initializers.lecun_normal(),
                                                    (HEADS, HEAD_DIM, EMBED)),
                               name="out_proj")(o)


class ClipEncoderLayer(nn.Module):
    hidden_size: int
    num_heads: int
    intermediate_size: int
    eps: float
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x, causal: bool):
        ln = lambda name: nn.LayerNorm(epsilon=self.eps, dtype=self.dtype,
                                       param_dtype=self.param_dtype, name=name)
        x = x + ClipAttention(self.hidden_size, self.num_heads, self.dtype,
                              self.param_dtype, name="self_attn")(ln("layer_norm1")(x), causal)
        dense = lambda feats, names, name: nn.Dense(
            feats, use_bias=True, dtype=self.dtype, param_dtype=self.param_dtype,
            kernel_init=_logical(nn.initializers.lecun_normal(), names), name=name)
        h = ln("layer_norm2")(x)
        h = dense(self.intermediate_size, (EMBED, MLP), "fc1")(h)
        h = quick_gelu(h)
        return x + dense(self.hidden_size, (MLP, EMBED), "fc2")(h)


class ClipTextModel(nn.Module):
    """Pre-LN causal text tower; returns (last_hidden_state, pooled) where
    pooled = the EOS token's final hidden state (HF CLIPTextModel)."""
    cfg: ClipTextConfig

    @nn.compact
    def __call__(self, input_ids):
        cfg = self.cfg
        tok = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype,
                       param_dtype=cfg.param_dtype,
                       embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                       name="token_embedding")(input_ids)
        pos = self.param("position_embedding",
                         _logical(nn.initializers.normal(0.01), ("pos", EMBED)),
                         (cfg.max_position_embeddings, cfg.hidden_size), cfg.param_dtype)
        x = tok + pos[None, :input_ids.shape[1]].astype(cfg.dtype)
        for i in range(cfg.num_hidden_layers):
            x = ClipEncoderLayer(cfg.hidden_size, cfg.num_attention_heads,
                                 cfg.intermediate_size, cfg.layer_norm_eps,
                                 cfg.dtype, cfg.param_dtype, name=f"layers_{i}")(x, causal=True)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="final_layer_norm")(x)
        # pooled = hidden state at the (first) EOS position per row
        eos_pos = jnp.argmax((input_ids == cfg.eos_token_id).astype(jnp.int32), axis=1)
        pooled = jnp.take_along_axis(x, eos_pos[:, None, None], axis=1)[:, 0]
        return x, pooled


class ClipVisionModel(nn.Module):
    """Patch-conv vision tower with class token; returns
    (last_hidden_state, pooled) where pooled = post-LN class embedding
    (HF CLIPVisionModel)."""
    cfg: ClipVisionConfig

    @nn.compact
    def __call__(self, pixel_values):
        cfg = self.cfg
        # pixel_values: [B, H, W, C] (NHWC — torch callers transpose NCHW)
        patches = nn.Conv(cfg.hidden_size, (cfg.patch_size, cfg.patch_size),
                          strides=(cfg.patch_size, cfg.patch_size), use_bias=False,
                          dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                          name="patch_embedding")(pixel_values)
        b, gh, gw, e = patches.shape
        x = patches.reshape(b, gh * gw, e)
        cls = self.param("class_embedding", nn.initializers.normal(0.02),
                         (cfg.hidden_size, ), cfg.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(cfg.dtype), (b, 1, e)), x], axis=1)
        pos = self.param("position_embedding", nn.initializers.normal(0.01),
                         (gh * gw + 1, cfg.hidden_size), cfg.param_dtype)
        x = x + pos[None].astype(cfg.dtype)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="pre_layrnorm")(x)
        for i in range(cfg.num_hidden_layers):
            x = ClipEncoderLayer(cfg.hidden_size, cfg.num_attention_heads,
                                 cfg.intermediate_size, cfg.layer_norm_eps,
                                 cfg.dtype, cfg.param_dtype, name=f"layers_{i}")(x, causal=False)
        pooled = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                              param_dtype=cfg.param_dtype, name="post_layernorm")(x[:, 0])
        return x, pooled


@dataclasses.dataclass(frozen=True)
class ClipConfig:
    """Bundle config for the dual encoder — carries the serving ``dtype``
    so the policy contract (rebuild via ``cfg.__class__(**cfg.__dict__)``)
    holds like every other family."""
    text: ClipTextConfig = ClipTextConfig()
    vision: ClipVisionConfig = ClipVisionConfig()
    projection_dim: int = 512
    dtype: Any = jnp.float32


class ClipModel(nn.Module):
    """Dual encoder + projections + temperature (HF CLIPModel): returns
    (logits_per_image, logits_per_text, text_embeds, image_embeds)."""
    text_cfg: ClipTextConfig
    vision_cfg: ClipVisionConfig
    projection_dim: int = 512

    @nn.compact
    def __call__(self, input_ids, pixel_values):
        _, tpool = ClipTextModel(self.text_cfg, name="text_model")(input_ids)
        _, vpool = ClipVisionModel(self.vision_cfg, name="vision_model")(pixel_values)
        proj = lambda name: nn.Dense(self.projection_dim, use_bias=False,
                                     dtype=jnp.float32, param_dtype=jnp.float32, name=name)
        t = proj("text_projection")(tpool.astype(jnp.float32))
        v = proj("visual_projection")(vpool.astype(jnp.float32))
        t = t / jnp.linalg.norm(t, axis=-1, keepdims=True)
        v = v / jnp.linalg.norm(v, axis=-1, keepdims=True)
        logit_scale = self.param("logit_scale", nn.initializers.constant(2.6592), ())
        scale = jnp.exp(logit_scale)
        logits_per_text = t @ v.T * scale
        return logits_per_text.T, logits_per_text, t, v
