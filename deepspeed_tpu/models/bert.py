"""BERT family (encoder + MLM/classification heads), TPU-first.

Reference coverage: BERT is the reference's original workhorse — the fused
transformer training kernels (``csrc/transformer/ds_transformer_cuda.cpp``,
exposed as ``DeepSpeedTransformerLayer``), the vendored test models
(``tests/unit/modeling.py``) and the BingBertSquad integration family.
Those kernels exist to fuse LN/softmax/dropout around cuBLAS matmuls — XLA
performs the same fusions from this plain flax definition, so the entire
7.6k-LoC kernel layer collapses into the model description.

Post-LN encoder (original BERT), learned positions, token-type embeddings,
GELU FFN, scan-over-layers + remat like the rest of the model zoo.
"""

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import EMBED, HEAD_DIM, HEADS, LAYERS, MLP, VOCAB, _logical

TYPES = "token_types"


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pre_layer_norm: bool = False  # post-LN (original BERT) by default;
    # pre-LN variant used by ops/transformer's stochastic/pre_layer_norm mode
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    scan_layers: bool = True
    remat: bool = True
    remat_policy: str = "nothing_saveable"

    @staticmethod
    def from_hf(hf_cfg, **overrides):
        fields = dict(
            vocab_size=hf_cfg.vocab_size,
            hidden_size=hf_cfg.hidden_size,
            num_hidden_layers=hf_cfg.num_hidden_layers,
            num_attention_heads=hf_cfg.num_attention_heads,
            intermediate_size=hf_cfg.intermediate_size,
            max_position_embeddings=hf_cfg.max_position_embeddings,
            type_vocab_size=getattr(hf_cfg, "type_vocab_size", 2),
            layer_norm_eps=getattr(hf_cfg, "layer_norm_eps", 1e-12),
        )
        fields.update(overrides)
        return BertConfig(**fields)


PRESETS = {
    "bert-base": BertConfig(),
    "bert-large": BertConfig(hidden_size=1024, num_hidden_layers=24, num_attention_heads=16,
                             intermediate_size=4096),
    "bert-tiny": BertConfig(vocab_size=30522, hidden_size=128, num_hidden_layers=2, num_attention_heads=2,
                            intermediate_size=512, max_position_embeddings=512),
}


def _ln(cfg, name):
    return nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                        scale_init=_logical(nn.initializers.ones_init(), (EMBED, )),
                        bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                        name=name)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, x, attention_mask=None):
        cfg = self.cfg
        head_dim = cfg.hidden_size // cfg.num_attention_heads
        dense = partial(nn.DenseGeneral,
                        features=(cfg.num_attention_heads, head_dim),
                        use_bias=True,
                        dtype=cfg.dtype,
                        param_dtype=cfg.param_dtype,
                        kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, HEADS, HEAD_DIM)),
                        bias_init=_logical(nn.initializers.zeros_init(), (HEADS, HEAD_DIM)))
        q = dense(name="query")(x)
        k = dense(name="key")(x)
        v = dense(name="value")(x)
        scale = 1.0 / jnp.sqrt(head_dim).astype(jnp.float32)
        logits = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
        if attention_mask is not None:
            # [B, S] 1=keep 0=pad (HF convention)
            logits = jnp.where(attention_mask[:, None, None, :] > 0, logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bnqk,bknd->bqnd", probs.astype(v.dtype), v)
        return nn.DenseGeneral(features=cfg.hidden_size,
                               axis=(-2, -1),
                               use_bias=True,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (HEADS, HEAD_DIM, EMBED)),
                               bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                               name="output")(out)


class BertLayer(nn.Module):
    cfg: BertConfig
    scanned: bool = False

    @nn.compact
    def __call__(self, x, attention_mask=None):
        cfg = self.cfg
        if cfg.pre_layer_norm:
            attn = BertSelfAttention(cfg, name="attention")(
                _ln(cfg, "attention_output_ln")(x), attention_mask)
            x = x + attn
            mlp_in = _ln(cfg, "output_ln")(x)
        else:
            attn = BertSelfAttention(cfg, name="attention")(x, attention_mask)
            x = _ln(cfg, "attention_output_ln")(x + attn)
            mlp_in = x
        h = nn.DenseGeneral(features=cfg.intermediate_size,
                            use_bias=True,
                            dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, MLP)),
                            bias_init=_logical(nn.initializers.zeros_init(), (MLP, )),
                            name="intermediate")(mlp_in)
        h = nn.gelu(h, approximate=False)
        h = nn.DenseGeneral(features=cfg.hidden_size,
                            use_bias=True,
                            dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.normal(0.02), (MLP, EMBED)),
                            bias_init=_logical(nn.initializers.zeros_init(), (EMBED, )),
                            name="output")(h)
        out = (x + h) if cfg.pre_layer_norm else _ln(cfg, "output_ln")(x + h)
        if self.scanned:
            return out, None
        return out


class BertModel(nn.Module):
    """Encoder trunk → final hidden states [B, S, H]."""
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None, positions=None):
        cfg = self.cfg
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(input_ids.shape[1]), input_ids.shape)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        embed = partial(nn.Embed, features=cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        x = embed(num_embeddings=cfg.vocab_size,
                  embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                  name="word_embeddings")(input_ids)
        x = x + embed(num_embeddings=cfg.max_position_embeddings,
                      embedding_init=_logical(nn.initializers.normal(0.02), (None, EMBED)),
                      name="position_embeddings")(positions)
        x = x + embed(num_embeddings=cfg.type_vocab_size,
                      embedding_init=_logical(nn.initializers.normal(0.02), (TYPES, EMBED)),
                      name="token_type_embeddings")(token_type_ids)
        x = _ln(cfg, "embeddings_ln")(x)

        layer_cls = BertLayer
        if cfg.remat:
            policy = getattr(jax.checkpoint_policies, cfg.remat_policy, None)
            layer_cls = nn.remat(BertLayer, policy=policy, prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            layers = nn.scan(layer_cls,
                             variable_axes={"params": 0},
                             split_rngs={"params": True},
                             in_axes=(nn.broadcast, ),
                             length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, _ = layers(cfg, scanned=True, name="encoder")(x, attention_mask)
        else:
            for i in range(cfg.num_hidden_layers):
                x = layer_cls(cfg, name=f"encoder_{i}")(x, attention_mask)
        return x


class BertForMaskedLM(nn.Module):
    """MLM head over the trunk (ref test analog: tests/unit/modeling.py
    BertForPreTraining minus NSP)."""
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None, positions=None):
        cfg = self.cfg
        x = BertModel(cfg, name="bert")(input_ids, attention_mask, token_type_ids, positions)
        x = nn.DenseGeneral(features=cfg.hidden_size,
                            use_bias=True,
                            dtype=cfg.dtype,
                            param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, None)),
                            name="transform")(x)
        x = nn.gelu(x, approximate=False)
        x = _ln(cfg, "transform_ln")(x)
        return nn.DenseGeneral(features=cfg.vocab_size,
                               use_bias=True,
                               dtype=cfg.dtype,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, VOCAB)),
                               name="decoder")(x)


class BertForSequenceClassification(nn.Module):
    cfg: BertConfig
    num_labels: int = 2

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, token_type_ids=None, positions=None):
        cfg = self.cfg
        x = BertModel(cfg, name="bert")(input_ids, attention_mask, token_type_ids, positions)
        pooled = jnp.tanh(nn.DenseGeneral(features=cfg.hidden_size,
                                          use_bias=True,
                                          dtype=cfg.dtype,
                                          param_dtype=cfg.param_dtype,
                                          kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, None)),
                                          name="pooler")(x[:, 0]))
        return nn.DenseGeneral(features=self.num_labels,
                               use_bias=True,
                               dtype=jnp.float32,
                               param_dtype=cfg.param_dtype,
                               kernel_init=_logical(nn.initializers.normal(0.02), (EMBED, None)),
                               name="classifier")(pooled)


def masked_lm_loss(logits, labels, loss_mask=None, ignore_index=-100):
    """MLM cross entropy; positions with ``ignore_index`` are skipped."""
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, safe_labels[..., None], axis=-1)[..., 0]
    mask = valid.astype(jnp.float32)
    if loss_mask is not None:
        mask = mask * loss_mask
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)
