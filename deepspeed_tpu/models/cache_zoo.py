"""Paged-KV serving twins for the non-llama model families.

ref: deepspeed/inference/v2/model_implementations/{falcon,opt,phi,qwen_v2_moe}
— the reference serves these arches through FastGen with per-arch policy +
container classes; here each gets a cache twin whose param tree mirrors its
training model exactly (so converted HF checkpoints apply unchanged) and
whose attention goes through the shared ``paged_attention_core``
(models/llama_cache.py): chunked forward, KV arena threaded through, one
program for prefill / continuation / decode.
"""

from functools import partial

import jax
import jax.numpy as jnp
from flax import linen as nn

from .llama import EMBED, HEAD_DIM, HEADS, KV_HEADS, LAYERS, MLP, VOCAB, RMSNorm, _logical, apply_rope, \
    rotary_embedding
from .llama_cache import paged_attention_core
from .falcon import FalconConfig
from .opt import OPTConfig
from .phi import PhiConfig, apply_partial_rope
from .qwen2_moe import Qwen2MoeConfig, Qwen2MoeSparseMLP


# ------------------------------------------------------------------- falcon


class FalconAttentionCache(nn.Module):
    cfg: FalconConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, x, positions, pages, block_table, start_pos, chunk_lens=None):
        cfg = self.cfg
        H, KV = cfg.num_attention_heads, cfg.num_kv_heads
        D = cfg.hidden_size // H
        dense = partial(nn.DenseGeneral, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        slopes = None
        if cfg.alibi:
            # falcon-rw: alibi position bias instead of rotary (same folding
            # as models/falcon.py's training path)
            from .falcon import alibi_slopes
            slopes = jnp.asarray(alibi_slopes(H))
        else:
            cos, sin = rotary_embedding(positions, D, cfg.rope_theta)
            q = apply_rope(q, cos, sin)
            k = apply_rope(k, cos, sin)
        out, pages = paged_attention_core(q, k, v, pages, block_table, start_pos, chunk_lens, self.page_size,
                                          attention_impl=cfg.attention_impl, alibi_slopes=slopes)
        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=cfg.bias,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                              name="dense")(out)
        return out, pages


class FalconBlockCache(nn.Module):
    cfg: FalconConfig
    page_size: int = 16
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        cfg = self.cfg
        x = carry
        ln = partial(nn.LayerNorm, epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype,
                     param_dtype=cfg.param_dtype)

        def mlp(mlp_in):
            ffn = cfg.ffn_hidden_size or cfg.hidden_size * 4
            h = nn.Dense(ffn, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)),
                         name="dense_h_to_4h")(mlp_in)
            return nn.Dense(cfg.hidden_size, use_bias=cfg.bias, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                            kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)),
                            name="dense_4h_to_h")(jax.nn.gelu(h, approximate=False))

        if not cfg.parallel_attn:
            # falcon-rw sequential residual: ln1 → attn → add; ln2 → mlp → add
            attn_in = ln(name="input_layernorm")(x)
            attn_out, layer_pages = FalconAttentionCache(cfg, self.page_size, name="self_attention")(
                attn_in, positions, layer_pages, block_table, start_pos, chunk_lens)
            h = x + attn_out
            return h + mlp(ln(name="post_attention_layernorm")(h)), layer_pages

        if cfg.num_ln_in_parallel_attn == 2:
            attn_in = ln(name="ln_attn")(x)
            mlp_in = ln(name="ln_mlp")(x)
        else:
            attn_in = ln(name="input_layernorm")(x)
            mlp_in = attn_in
        attn_out, layer_pages = FalconAttentionCache(cfg, self.page_size, name="self_attention")(
            attn_in, positions, layer_pages, block_table, start_pos, chunk_lens)
        return x + attn_out + mlp(mlp_in), layer_pages


class FalconForCausalLMWithCache(nn.Module):
    cfg: FalconConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="word_embeddings")
        x = embed(input_ids)
        blocks = nn.scan(FalconBlockCache, variable_axes={"params": 0}, split_rngs={"params": True},
                         in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                         out_axes=0, length=cfg.num_hidden_layers,
                         metadata_params={nn.PARTITION_NAME: LAYERS})
        x, cache = blocks(cfg, self.page_size, scanned=True,
                          name="h")(x, cache, positions, block_table, start_pos, chunk_lens)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_epsilon, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="ln_f")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x), cache
        logits = nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                                 param_dtype=cfg.param_dtype,
                                 kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                 name="lm_head")(x)
        return logits, cache


# ---------------------------------------------------------------------- opt


class OPTAttentionCache(nn.Module):
    cfg: OPTConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, x, pages, block_table, start_pos, chunk_lens=None):
        cfg = self.cfg
        H = cfg.num_attention_heads
        D = cfg.hidden_size // H
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        out, pages = paged_attention_core(q, k, v, pages, block_table, start_pos, chunk_lens, self.page_size,
                                          attention_impl=cfg.attention_impl)
        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                              name="out_proj")(out)
        return out, pages


class OPTBlockCache(nn.Module):
    cfg: OPTConfig
    page_size: int = 16
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        cfg = self.cfg
        x = carry
        ln = partial(nn.LayerNorm, epsilon=1e-5, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        a_in = ln(name="self_attn_layer_norm")(x) if cfg.do_layer_norm_before else x
        a, layer_pages = OPTAttentionCache(cfg, self.page_size, name="self_attn")(
            a_in, layer_pages, block_table, start_pos, chunk_lens)
        h = x + a
        if not cfg.do_layer_norm_before:
            h = ln(name="self_attn_layer_norm")(h)
        m_in = ln(name="final_layer_norm")(h) if cfg.do_layer_norm_before else h
        m = nn.Dense(cfg.ffn_dim, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)), name="fc1")(m_in)
        m = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)),
                     name="fc2")(jax.nn.relu(m))
        out = h + m
        if not cfg.do_layer_norm_before:
            out = ln(name="final_layer_norm")(out)
        return out, layer_pages


class OPTForCausalLMWithCache(nn.Module):
    cfg: OPTConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        proj_dim = cfg.word_embed_proj_dim or cfg.hidden_size
        embed = nn.Embed(cfg.vocab_size, proj_dim, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        pos_embed = nn.Embed(cfg.max_position_embeddings + 2, cfg.hidden_size, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, embedding_init=nn.initializers.normal(0.02),
                             name="embed_positions")
        # pad-region positions can exceed the learned table (prefill chunk >
        # max_position): clamp — jnp.take would otherwise FILL (NaN)
        safe_pos = jnp.minimum(positions, cfg.max_position_embeddings - 1)
        x = embed(input_ids)
        if proj_dim != cfg.hidden_size:
            x = nn.Dense(cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_in")(x)
        x = x + pos_embed(safe_pos + 2)
        blocks = nn.scan(OPTBlockCache, variable_axes={"params": 0}, split_rngs={"params": True},
                         in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                         out_axes=0, length=cfg.num_hidden_layers,
                         metadata_params={nn.PARTITION_NAME: LAYERS})
        x, cache = blocks(cfg, self.page_size, scanned=True,
                          name="layers")(x, cache, positions, block_table, start_pos, chunk_lens)
        if cfg.do_layer_norm_before:
            x = nn.LayerNorm(epsilon=1e-5, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                             name="final_layer_norm")(x)
        if proj_dim != cfg.hidden_size:
            x = nn.Dense(proj_dim, use_bias=False, dtype=cfg.dtype,
                         param_dtype=cfg.param_dtype, name="project_out")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x), cache
        logits = nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                                 param_dtype=cfg.param_dtype,
                                 kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                 name="lm_head")(x)
        return logits, cache


# ---------------------------------------------------------------------- phi


class PhiAttentionCache(nn.Module):
    cfg: PhiConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, x, positions, pages, block_table, start_pos, chunk_lens=None):
        cfg = self.cfg
        H, KV = cfg.num_attention_heads, cfg.num_key_value_heads
        D = cfg.hidden_size // H
        rot_dim = int(D * cfg.partial_rotary_factor)
        dense = partial(nn.DenseGeneral, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense(features=(H, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, HEADS, HEAD_DIM)),
                  name="q_proj")(x)
        k = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="k_proj")(x)
        v = dense(features=(KV, D), kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, KV_HEADS, HEAD_DIM)),
                  name="v_proj")(x)
        if cfg.qk_layernorm:
            q = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="q_layernorm")(q)
            k = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype,
                             param_dtype=cfg.param_dtype, name="k_layernorm")(k)
        cos, sin = rotary_embedding(positions, rot_dim, cfg.rope_theta)
        q = apply_partial_rope(q, cos, sin, rot_dim)
        k = apply_partial_rope(k, cos, sin, rot_dim)
        out, pages = paged_attention_core(q, k, v, pages, block_table, start_pos, chunk_lens, self.page_size,
                                          attention_impl=cfg.attention_impl)
        out = nn.DenseGeneral(features=cfg.hidden_size, axis=(-2, -1), use_bias=True,
                              dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                              kernel_init=_logical(nn.initializers.lecun_normal(), (HEADS, HEAD_DIM, EMBED)),
                              name="dense")(out)
        return out, pages


class PhiBlockCache(nn.Module):
    cfg: PhiConfig
    page_size: int = 16
    scanned: bool = False

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        cfg = self.cfg
        x = carry
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="input_layernorm")(x)
        attn_out, layer_pages = PhiAttentionCache(cfg, self.page_size, name="self_attn")(
            h, positions, layer_pages, block_table, start_pos, chunk_lens)
        m = nn.Dense(cfg.intermediate_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                     kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, MLP)), name="fc1")(h)
        m = jax.nn.gelu(m, approximate=True)
        mlp_out = nn.Dense(cfg.hidden_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                           kernel_init=_logical(nn.initializers.lecun_normal(), (MLP, EMBED)), name="fc2")(m)
        return x + attn_out + mlp_out, layer_pages


class PhiForCausalLMWithCache(nn.Module):
    cfg: PhiConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        blocks = nn.scan(PhiBlockCache, variable_axes={"params": 0}, split_rngs={"params": True},
                         in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                         out_axes=0, length=cfg.num_hidden_layers,
                         metadata_params={nn.PARTITION_NAME: LAYERS})
        x, cache = blocks(cfg, self.page_size, scanned=True,
                          name="layers")(x, cache, positions, block_table, start_pos, chunk_lens)
        x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         name="final_layernorm")(x)
        logits = nn.Dense(cfg.vocab_size, use_bias=True, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                          kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                          name="lm_head")(x)
        return logits, cache


# ---------------------------------------------------------------- qwen2-moe


class Qwen2MoeBlockCache(nn.Module):
    cfg: Qwen2MoeConfig
    page_size: int = 16
    scanned: bool = False
    sparse: bool = True   # mixed stacks: dense SwiGLU for mlp_only/off-step layers

    @nn.compact
    def __call__(self, carry, layer_pages, positions=None, block_table=None, start_pos=None, chunk_lens=None):
        from .llama_cache import LlamaAttentionCache
        from .qwen2_moe import Qwen2MoeDenseMLP
        cfg = self.cfg
        x = carry
        attn_out, layer_pages = LlamaAttentionCache(cfg.as_llama(), self.page_size, name="self_attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="input_layernorm")(x), positions,
            layer_pages, block_table, start_pos, chunk_lens)
        h = x + attn_out
        mlp = Qwen2MoeSparseMLP(cfg, name="mlp") if self.sparse else Qwen2MoeDenseMLP(cfg, name="mlp")
        out = h + mlp(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="post_attention_layernorm")(h))
        return out, layer_pages


class Qwen2MoeForCausalLMWithCache(nn.Module):
    cfg: Qwen2MoeConfig
    page_size: int = 16

    @nn.compact
    def __call__(self, input_ids, start_pos, block_table, cache, chunk_lens=None):
        cfg = self.cfg
        positions = start_pos[:, None] + jnp.arange(input_ids.shape[1])[None, :]
        embed = nn.Embed(cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=cfg.param_dtype,
                         embedding_init=_logical(nn.initializers.normal(0.02), (VOCAB, EMBED)),
                         name="embed_tokens")
        x = embed(input_ids)
        if cfg.mixed_stack:
            # dense/sparse layers can't share one scanned body — unroll with
            # per-layer dispatch, mirroring the training model's layers_{i}
            # naming so converted checkpoints apply unchanged
            new_pages = []
            for i in range(cfg.num_hidden_layers):
                x, pages_i = Qwen2MoeBlockCache(cfg, self.page_size, sparse=cfg.layer_is_sparse(i),
                                                name=f"layers_{i}")(x, cache[i], positions,
                                                                    block_table, start_pos, chunk_lens)
                new_pages.append(pages_i)
            cache = jnp.stack(new_pages)
        else:
            blocks = nn.scan(Qwen2MoeBlockCache, variable_axes={"params": 0}, split_rngs={"params": True},
                             in_axes=(0, nn.broadcast, nn.broadcast, nn.broadcast, nn.broadcast),
                             out_axes=0, length=cfg.num_hidden_layers,
                             metadata_params={nn.PARTITION_NAME: LAYERS})
            x, cache = blocks(cfg, self.page_size, scanned=True,
                              name="layers")(x, cache, positions, block_table, start_pos, chunk_lens)
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, cfg.param_dtype, name="norm")(x)
        if cfg.tie_word_embeddings:
            return embed.attend(x), cache
        logits = nn.DenseGeneral(features=cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                                 param_dtype=cfg.param_dtype,
                                 kernel_init=_logical(nn.initializers.lecun_normal(), (EMBED, VOCAB)),
                                 name="lm_head")(x)
        return logits, cache


CACHE_MODEL_REGISTRY = {
    FalconConfig: FalconForCausalLMWithCache,
    OPTConfig: OPTForCausalLMWithCache,
    PhiConfig: PhiForCausalLMWithCache,
    Qwen2MoeConfig: Qwen2MoeForCausalLMWithCache,
}
