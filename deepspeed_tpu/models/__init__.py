"""Model zoo (TPU-native analogs of the reference's model coverage:
module_inject containers + inference/v2/model_implementations)."""

from .llama import LlamaConfig, LlamaForCausalLM, causal_lm_loss  # noqa: F401
from .gpt2 import GPT2Config, GPT2LMHeadModel  # noqa: F401
from .bert import (BertConfig, BertForMaskedLM, BertForSequenceClassification,  # noqa: F401
                   BertModel, masked_lm_loss)
from .mixtral import MixtralConfig, MixtralForCausalLM, make_mixtral_loss_fn, mixtral_lm_loss  # noqa: F401
