"""Flow-sensitive analysis substrate (r17): per-function CFGs with
exception edges (:mod:`.cfg`) and a project call-graph index
(:mod:`.callgraph`), built once per dslint run and shared by the three
flow checkers (kv-lifetime, state-machine, crash-transparency-interproc).

Kept import-light on purpose: like the rest of ``analysis/``, nothing
here may import jax or the serving package — dslint's whole-repo run
budget depends on it (docs/ANALYSIS.md)."""

from .callgraph import ProjectIndex, RELEASE_NAMES, TRANSFER_NAMES, call_name
from .cfg import CFG, build_cfg

__all__ = ["CFG", "build_cfg", "ProjectIndex", "RELEASE_NAMES",
           "TRANSFER_NAMES", "call_name"]


def project_index(run) -> ProjectIndex:
    """The run-wide index, built lazily on first use and cached on the
    Runner — every flow checker's ``finish`` shares one build."""
    idx = getattr(run, "_flow_index", None)
    if idx is None:
        idx = run._flow_index = ProjectIndex.build(run.contexts)
    return idx
