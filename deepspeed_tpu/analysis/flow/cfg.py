"""Per-function control-flow graph with exception edges.

One :class:`CFG` per ``def``: nodes are *simple statements* (compound
statements contribute a **head** node carrying only their test/iter
expressions), edges split into normal flow (``succ``) and exception flow
(``esucc``).  Three synthetic nodes anchor the graph: ``entry``, ``exit``
(every ``return`` and normal fall-off), and ``raise_exit`` (an exception
leaving the function).  This is what lets the flow checkers ask the
question the single-AST-walk checkers structurally cannot: *does every
path from HERE — including the raise paths — pass through one of THESE
nodes before leaving the function?*

Exception-edge model (documented over-approximation, tuned to this
repo's invariants rather than the full language):

* a statement **can raise** iff its own expressions contain a ``Call``,
  ``Subscript``, ``Await``, ``Raise`` or ``Assert`` — the things that
  actually throw in this codebase (engine ops, fault-injection probes,
  ``dict``/page-table lookups).  Attribute reads and arithmetic are
  treated as total.
* a raising statement's exception edge goes to every handler of the
  innermost enclosing ``try`` (any handler *could* match) and — unless
  one of the handlers is broad (bare / ``Exception`` / ``BaseException``)
  — onward to the next level out;
* ``finally`` blocks are duplicated per continuation kind (normal /
  exception / return / break / continue), so a path through ``finally``
  cannot teleport between continuations — a body that completes normally
  can never appear to jump to the function exit through the exception
  copy of the ``finally``.

Determinism: node indices follow source order, successor sets are
iterated sorted, and the builder touches no global state — two builds of
the same function are structurally identical.
"""

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

_BROAD_NAMES = ("Exception", "BaseException")


@dataclasses.dataclass
class Node:
    """One CFG node.  ``stmt`` is None for synthetic nodes (entry/exit/
    raise_exit/finally joins); ``exprs`` holds only the expressions that
    belong to THIS node (a compound statement's head excludes its body),
    so checkers walk ``exprs``, never ``stmt`` wholesale."""
    idx: int
    stmt: Optional[ast.AST]
    kind: str                    # "stmt" | "entry" | "exit" | "raise"
    exprs: Tuple[ast.AST, ...] = ()
    succ: Set[int] = dataclasses.field(default_factory=set)
    esucc: Set[int] = dataclasses.field(default_factory=set)

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


@dataclasses.dataclass
class _Frame:
    """Where the non-local continuations of the current statement list go
    (already routed through any enclosing ``finally`` copies)."""
    ret: int                     # target of `return`
    exc: Tuple[int, ...]         # exception targets (handlers + escape)
    brk: Optional[int] = None
    cont: Optional[int] = None


def _type_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _catches_all(handlers: Sequence[ast.ExceptHandler]) -> bool:
    for h in handlers:
        if h.type is None or _type_name(h.type) in _BROAD_NAMES:
            return True
        if isinstance(h.type, ast.Tuple) and \
                any(_type_name(e) in _BROAD_NAMES for e in h.type.elts):
            return True
    return False


def _can_raise(exprs: Sequence[ast.AST]) -> bool:
    for e in exprs:
        for n in ast.walk(e):
            if isinstance(n, (ast.Call, ast.Subscript, ast.Await)):
                return True
    return False


class CFG:
    def __init__(self, func: ast.AST):
        self.func = func
        self.nodes: List[Node] = []
        #: finally-copy join -> that copy's live-outs (normal continuation
        #: copies are wired by the caller once the after-set is known)
        self._copy_outs: Dict[int, List[int]] = {}
        self.entry = self._new(None, "entry")
        self.exit = self._new(None, "exit")
        self.raise_exit = self._new(None, "raise")
        frame = _Frame(ret=self.exit, exc=(self.raise_exit, ))
        outs = self._stmts(func.body, [self.entry], frame)
        for o in outs:
            self.nodes[o].succ.add(self.exit)

    # ------------------------------------------------------------ plumbing

    def _new(self, stmt, kind="stmt", exprs=()) -> int:
        n = Node(idx=len(self.nodes), stmt=stmt, kind=kind,
                 exprs=tuple(exprs))
        self.nodes.append(n)
        return n.idx

    def _connect(self, preds: Sequence[int], target: int) -> None:
        for p in preds:
            self.nodes[p].succ.add(target)

    def _stmt_node(self, stmt, frame: _Frame, exprs) -> int:
        idx = self._new(stmt, "stmt", exprs)
        if _can_raise(exprs) or isinstance(stmt, (ast.Raise, ast.Assert)):
            self.nodes[idx].esucc.update(frame.exc)
        return idx

    # ---------------------------------------------------------- statements

    def _stmts(self, body: Sequence[ast.stmt], preds: List[int],
               frame: _Frame) -> List[int]:
        """Wire ``body`` after ``preds``; returns the live-out node set
        (empty when every path diverted: return/raise/break/continue)."""
        cur = list(preds)
        for stmt in body:
            if not cur:
                break  # unreachable code: keep walk cheap, skip it
            cur = self._stmt(stmt, cur, frame)
        return cur

    def _stmt(self, stmt: ast.stmt, preds: List[int],
              frame: _Frame) -> List[int]:
        if isinstance(stmt, ast.If):
            head = self._stmt_node(stmt, frame, [stmt.test])
            self._connect(preds, head)
            outs = self._stmts(stmt.body, [head], frame)
            if stmt.orelse:
                outs += self._stmts(stmt.orelse, [head], frame)
            else:
                outs.append(head)
            return outs
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, preds, frame)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds, frame)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            head = self._stmt_node(stmt, frame,
                                   [i.context_expr for i in stmt.items])
            self._connect(preds, head)
            return self._stmts(stmt.body, [head], frame)
        if hasattr(ast, "Match") and isinstance(stmt, ast.Match):
            head = self._stmt_node(stmt, frame, [stmt.subject])
            self._connect(preds, head)
            outs = [head]  # no case may match
            for case in stmt.cases:
                outs += self._stmts(case.body, [head], frame)
            return outs
        if isinstance(stmt, ast.Return):
            exprs = [stmt.value] if stmt.value is not None else []
            idx = self._stmt_node(stmt, frame, exprs)
            self._connect(preds, idx)
            self.nodes[idx].succ.add(frame.ret)
            return []
        if isinstance(stmt, ast.Raise):
            exprs = [e for e in (stmt.exc, stmt.cause) if e is not None]
            idx = self._stmt_node(stmt, frame, exprs)
            self._connect(preds, idx)
            self.nodes[idx].esucc.update(frame.exc)
            return []
        if isinstance(stmt, ast.Break):
            idx = self._stmt_node(stmt, frame, [])
            self._connect(preds, idx)
            if frame.brk is not None:
                self.nodes[idx].succ.add(frame.brk)
            return []
        if isinstance(stmt, ast.Continue):
            idx = self._stmt_node(stmt, frame, [])
            self._connect(preds, idx)
            if frame.cont is not None:
                self.nodes[idx].succ.add(frame.cont)
            return []
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            # nested scope: a single opaque node (decorators/defaults run
            # here; the body is someone else's CFG)
            exprs = list(stmt.decorator_list)
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                exprs += [d for d in stmt.args.defaults if d is not None]
            idx = self._stmt_node(stmt, frame, exprs)
            self._connect(preds, idx)
            return [idx]
        # simple statement: Assign/AugAssign/AnnAssign/Expr/Assert/Delete/
        # Import/Global/Nonlocal/Pass — one node carrying itself
        idx = self._stmt_node(stmt, frame, [stmt])
        self._connect(preds, idx)
        if isinstance(stmt, ast.Assert):
            self.nodes[idx].esucc.update(frame.exc)  # a failing assert raises
        return [idx]

    def _loop(self, stmt, preds: List[int], frame: _Frame) -> List[int]:
        exprs = [stmt.test] if isinstance(stmt, ast.While) \
            else [stmt.target, stmt.iter]
        head = self._stmt_node(stmt, frame, exprs)
        self._connect(preds, head)
        after: List[int] = []
        infinite = isinstance(stmt, ast.While) \
            and isinstance(stmt.test, ast.Constant) and bool(stmt.test.value)
        join = self._new(None, "stmt")  # break target placeholder
        inner = dataclasses.replace(frame, brk=join, cont=head)
        body_outs = self._stmts(stmt.body, [head], inner)
        self._connect(body_outs, head)       # loop back edge
        if stmt.orelse:
            after += self._stmts(stmt.orelse, [head], frame)
        elif not infinite:
            after.append(head)               # test false / iterator empty
        after.append(join)
        return after

    def _try(self, stmt: ast.Try, preds: List[int],
             frame: _Frame) -> List[int]:
        # finally copies, one per continuation kind — a synthetic join
        # node enters each copy and the copy's live-outs land on that
        # continuation's ORIGINAL target only, so a normally-completing
        # body can never appear to jump to the function exit through the
        # exception copy of the finally
        if stmt.finalbody:
            fin_exc = self._finally_copy(stmt, list(frame.exc), frame)
            exc_escape = (fin_exc, )
            ret_target = self._finally_copy(stmt, [frame.ret], frame)
            brk_target = self._finally_copy(stmt, [frame.brk], frame) \
                if frame.brk is not None else None
            cont_target = self._finally_copy(stmt, [frame.cont], frame) \
                if frame.cont is not None else None
        else:
            exc_escape = frame.exc
            ret_target = frame.ret
            brk_target, cont_target = frame.brk, frame.cont

        handler_heads: List[int] = []
        for h in stmt.handlers:
            exprs = [h.type] if h.type is not None else []
            handler_heads.append(self._new(h, "stmt", exprs))
        body_exc = tuple(handler_heads) + \
            (() if _catches_all(stmt.handlers) else tuple(exc_escape))
        body_frame = _Frame(ret=ret_target, exc=body_exc,
                            brk=brk_target, cont=cont_target)
        body_outs = self._stmts(stmt.body, preds, body_frame)

        outer_frame = _Frame(ret=ret_target, exc=tuple(exc_escape),
                             brk=brk_target, cont=cont_target)
        outs: List[int] = []
        for head_idx, h in zip(handler_heads, stmt.handlers):
            outs += self._stmts(h.body, [head_idx], outer_frame)
        if stmt.orelse:
            outs += self._stmts(stmt.orelse, body_outs, outer_frame)
        else:
            outs += body_outs
        if stmt.finalbody:
            fin_norm = self._finally_copy(stmt, [], frame)
            self._connect(outs, fin_norm)
            return self._copy_outs.pop(fin_norm)
        return outs

    def _finally_copy(self, stmt: ast.Try, targets: List[int],
                      frame: _Frame) -> int:
        """Build one duplicate of ``stmt.finalbody`` entered via a fresh
        join node; its live-outs connect to ``targets`` (empty = the
        caller wires them itself via ``_copy_outs``)."""
        join = self._new(None, "stmt")
        f = _Frame(ret=frame.ret, exc=frame.exc,
                   brk=frame.brk, cont=frame.cont)
        outs = self._stmts(stmt.finalbody, [join], f)
        for o in outs:
            for t in targets:
                self.nodes[o].succ.add(t)
        if not targets:
            self._copy_outs[join] = outs
        return join

    # ------------------------------------------------------------- queries

    def reach_escape(self, start: int, kills: Set[int]) -> Optional[str]:
        """From node ``start``'s *normal* successors (an exception inside
        the start statement itself means the resource was never acquired),
        follow both flow and exception edges; return ``"exit"`` /
        ``"raise"`` for the first function escape reachable without
        passing through a ``kills`` node, or None when every path is
        killed first.  Deterministic: successors visited in sorted order,
        exit checked before raise."""
        seen: Set[int] = set()
        stack = sorted(self.nodes[start].succ)
        escapes: Set[str] = set()
        while stack:
            idx = stack.pop()
            if idx in seen or idx in kills:
                continue
            seen.add(idx)
            node = self.nodes[idx]
            if node.kind == "exit":
                escapes.add("exit")
                continue
            if node.kind == "raise":
                escapes.add("raise")
                continue
            stack.extend(sorted(node.succ | node.esucc))
        if "exit" in escapes:
            return "exit"
        if "raise" in escapes:
            return "raise"
        return None


def build_cfg(func: ast.AST) -> CFG:
    return CFG(func)
