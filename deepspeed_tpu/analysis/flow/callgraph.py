"""Project call-graph index, built once per dslint run.

The flow checkers need three cross-file answers the per-file walk cannot
give:

* *who is this call?* — resolve a call site to the project function(s) it
  plausibly names (import-alias dotted path, same-file bare name, or a
  ``self.method`` against the enclosing class);
* *does the callee consume this argument?* — which parameters of each
  function are **consuming**: released / ownership-transferred inside the
  body (directly, or by forwarding to another consuming function — a
  deterministic fixpoint over the sorted function list);
* *does the callee swallow broad exceptions?* — the crash-transparency
  facts of each function body, so the interprocedural checker can follow
  a guarded handler one call-hop down.

Everything is indexed from the ``FileContext`` objects the Runner already
holds, so the index costs one extra pass over already-parsed ASTs.  All
iteration orders are sorted — the index is deterministic for a given file
set regardless of argument order (asserted in tier-1).
"""

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: call names that end a tracked resource's lifetime when it appears as an
#: argument: allocator/page release and rollback ...
RELEASE_NAMES = frozenset({"free", "release", "release_tail", "truncate"})
#: ... and ownership transfer: registration into a cache/descriptor/
#: container, or handing the staged payload to an importer that owns its
#: own failure cleanup
TRANSFER_NAMES = frozenset({
    "adopt", "register", "extend", "append", "insert", "add", "add_chunk",
    "import_prefix", "import_snapshot", "import_pages", "put", "submit",
    "SequenceDescriptor",
})
SINK_NAMES = RELEASE_NAMES | TRANSFER_NAMES


@dataclasses.dataclass
class FunctionInfo:
    rel: str                      # root-relative path of the defining file
    module: str                   # dotted module tail ("serving.engine")
    qualname: str                 # "Class.method" or "func"
    name: str
    cls: Optional[str]
    lineno: int
    node: ast.AST
    params: Tuple[str, ...]       # positional-or-keyword names, self included
    consuming: Set[str] = dataclasses.field(default_factory=set)
    #: (lineno, description) per broad handler that can absorb an
    #: exception (not guarded, not unavoidably re-raising)
    swallows: List[Tuple[int, str]] = dataclasses.field(default_factory=list)


def _module_of(rel: str) -> str:
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    for prefix in ("deepspeed_tpu.", ):
        if mod.startswith(prefix):
            mod = mod[len(prefix):]
    if mod.endswith(".__init__"):
        mod = mod[:-len(".__init__")]
    return mod


def call_name(func: ast.AST) -> str:
    """Terminal name of a call target: ``kv.allocator.allocate`` ->
    ``allocate``; bare ``export_prefix`` -> ``export_prefix``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


class ProjectIndex:
    """All function definitions across the scanned files."""

    def __init__(self):
        self.functions: List[FunctionInfo] = []
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        self.by_rel: Dict[str, List[FunctionInfo]] = {}
        #: rel -> the file's import-alias map (FileContext.imports)
        self.imports_by_rel: Dict[str, dict] = {}

    # ------------------------------------------------------------ building

    @classmethod
    def build(cls, contexts) -> "ProjectIndex":
        """``contexts``: mapping rel -> FileContext (parsed)."""
        index = cls()
        for rel in sorted(contexts):
            ctx = contexts[rel]
            if ctx.tree is None:
                continue
            index.imports_by_rel[rel] = dict(ctx.imports)
            index._collect_file(rel, ctx.tree)
        index.functions.sort(key=lambda f: (f.rel, f.lineno, f.qualname))
        for f in index.functions:
            index.by_name.setdefault(f.name, []).append(f)
            index.by_rel.setdefault(f.rel, []).append(f)
        index._consuming_fixpoint()
        return index

    def _collect_file(self, rel: str, tree: ast.AST) -> None:
        module = _module_of(rel)

        def walk(node, cls_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    params = tuple(a.arg for a in
                                   child.args.posonlyargs + child.args.args)
                    info = FunctionInfo(
                        rel=rel, module=module, qualname=qual,
                        name=child.name, cls=cls_name,
                        lineno=child.lineno, node=child, params=params)
                    info.consuming = _direct_consuming(child, params)
                    info.swallows = _swallowing_handlers(child)
                    self.functions.append(info)
                    walk(child, cls_name, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    walk(child, child.name, child.name + ".")
                else:
                    walk(child, cls_name, prefix)

        walk(tree, None, "")

    def _consuming_fixpoint(self) -> None:
        """Propagate consumption through forwarding helpers: if ``f(p)``
        passes ``p`` to a consuming parameter of ``g``, then ``p`` is
        consuming in ``f`` too.  Iterated to a fixpoint (bounded by the
        total parameter count; function order is sorted, so the result is
        order-independent)."""
        changed = True
        guard = 0
        while changed and guard < 20:
            changed = False
            guard += 1
            for f in self.functions:
                for call in ast.walk(f.node):
                    if not isinstance(call, ast.Call):
                        continue
                    for param in f.params:
                        if param in f.consuming:
                            continue
                        if self._call_consumes(call, param, f):
                            f.consuming.add(param)
                            changed = True

    def _call_consumes(self, call: ast.Call, name: str,
                       caller: Optional[FunctionInfo] = None) -> bool:
        """Does this call consume the plain-Name argument ``name``?"""
        cname = call_name(call.func)
        pos = None
        kw = None
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Name) and a.id == name:
                pos = i
        for k in call.keywords:
            if isinstance(k.value, ast.Name) and k.value.id == name \
                    and k.arg is not None:
                kw = k.arg
        if pos is None and kw is None:
            return False
        if cname in SINK_NAMES:
            return True
        imports = self.imports_by_rel.get(caller.rel) if caller else None
        for target in self.resolve(call, caller, imports=imports):
            params = target.params
            if params and params[0] == "self" and \
                    not isinstance(call.func, ast.Name):
                params = params[1:]
            if pos is not None and pos < len(params) \
                    and params[pos] in target.consuming:
                return True
            if kw is not None and kw in target.consuming:
                return True
        return False

    # ----------------------------------------------------------- resolving

    def resolve(self, call: ast.Call,
                caller: Optional[FunctionInfo] = None,
                imports: Optional[dict] = None) -> List[FunctionInfo]:
        """Project functions a call site plausibly names.  Conservative:
        bare names match same-file functions; ``self.m()`` matches methods
        of the caller's class; dotted/imported names match by module tail
        + function name (``imports`` is the FileContext alias map)."""
        func = call.func
        out: List[FunctionInfo] = []
        if isinstance(func, ast.Name):
            dotted = (imports or {}).get(func.id, func.id)
            name = dotted.split(".")[-1]
            # "kvtransfer.export_prefix" (a package re-export) must still
            # find serving/kvtransfer/snapshot.py — match the import's
            # module segment against any segment of the defining module
            mod_seg = dotted.rsplit(".", 1)[0].split(".")[-1] \
                if "." in dotted else None
            for cand in self.by_name.get(name, ()):
                if cand.cls is not None:
                    continue
                if caller is not None and cand.rel == caller.rel:
                    out.append(cand)
                elif mod_seg is not None and \
                        mod_seg in cand.module.split("."):
                    out.append(cand)
        elif isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and caller is not None and caller.cls is not None:
                for cand in self.by_name.get(func.attr, ()):
                    if cand.rel == caller.rel and cand.cls == caller.cls:
                        out.append(cand)
            elif isinstance(base, ast.Name) and imports is not None:
                # module-attribute call through an import alias:
                # ``_fi.check(...)`` after ``import fault_injection as _fi``
                dotted_mod = imports.get(base.id, base.id)
                tail = dotted_mod.split(".")[-1]
                for cand in self.by_name.get(func.attr, ()):
                    if cand.cls is None and cand.module.split(".")[-1] == tail:
                        out.append(cand)
        return out


# -------------------------------------------------- per-function fact pass


def _direct_consuming(func: ast.AST, params: Sequence[str]) -> Set[str]:
    """Parameters directly released/transferred in ``func``'s own body:
    passed to a RELEASE/TRANSFER-named call, stored into an attribute or
    subscript, or returned/yielded."""
    wanted = set(params) - {"self", "cls"}
    out: Set[str] = set()
    if not wanted:
        return out
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and call_name(node.func) in SINK_NAMES:
            for a in list(node.args) + [k.value for k in node.keywords]:
                for n in ast.walk(a):
                    if isinstance(n, ast.Name) and n.id in wanted:
                        out.add(n.id)
        elif isinstance(node, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in node.targets):
                for n in ast.walk(node.value):
                    if isinstance(n, ast.Name) and n.id in wanted:
                        out.add(n.id)
        elif isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id in wanted:
                    out.add(n.id)
    return out


# crash-transparency handler facts (shared shape with the r11 checker —
# imported from it so the two stay one rule)
def _swallowing_handlers(func: ast.AST) -> List[Tuple[int, str]]:
    from ..checkers.crash_transparency import (_is_broad, _is_crash_guard,
                                               _reraises)
    out: List[Tuple[int, str]] = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Try):
            continue
        guarded = False
        for handler in node.handlers:
            if _is_crash_guard(handler):
                guarded = True
                continue
            if not _is_broad(handler):
                continue
            if guarded or _reraises(handler):
                continue
            caught = "bare except" if handler.type is None else \
                f"except {ast.unparse(handler.type)}"
            out.append((handler.lineno, caught))
    return out
