"""dslint: unified static analysis enforcing the stack's conventions as
machine-checked contracts (r11 tentpole).

The determinism / crash-transparency / registry invariants PRs 1-10 stake
their correctness on were conventions until this package: bit-reproducible
traces require no wall-clock reads outside the pluggable clock modules,
chaos tests require ``InjectedCrash`` to never be absorbed by a broad
``except``, and the fault-site / event-name taxonomies drift silently from
their call sites.  ``analysis/`` runs every checker in ONE AST walk per
file, emits deterministic sorted findings (human + JSON), and supports
per-line suppressions with a mandatory written reason::

    something_flagged()  # dslint-ok(<checker>): <why this is fine>

Entry points: ``scripts/dslint.py`` (CLI, exit 1 on findings) and
``tests/unit/test_dslint.py`` (tier-1: the repo stays lint-clean).

NOTE this package is import-standalone on purpose: it must never import
``deepspeed_tpu`` (jax, numpy, ...) so the lint runs in well under the 5 s
budget.  ``scripts/dslint.py`` imports it as the top-level package
``analysis`` by putting the ``deepspeed_tpu/`` directory itself on
``sys.path`` — keep all internal imports relative.
"""

from .core import Finding, Runner, collect_files  # noqa: F401
from .checkers import all_checkers, checker_names  # noqa: F401
