"""Framework: one AST walk per file, pluggable checkers, deterministic
findings, suppression markers with mandatory reasons.

A checker subclasses :class:`Checker` and gets three hooks:

* ``visit(node, ctx)``   — called for every AST node of every file it
  ``applies()`` to, during the file's single walk;
* ``end_file(ctx)``      — after a file's walk;
* ``finish(run)``        — once, after all files (cross-file contracts:
  registry reconciliation, doc sync, non-AST artifacts).

Findings are reported through ``ctx.report`` / ``run.report`` so the
suppression check (``# dslint-ok(<checker>): <reason>``) is applied in one
place.  A marker without a reason, or naming an unknown checker, is itself
a finding (checker ``suppression``) — a suppression is a written-down
decision, not an off switch.
"""

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# the reason is lazy and stops before the next marker so several markers
# sharing a line each keep their own reason
SUPPRESS_RE = re.compile(
    r"#\s*dslint-ok\(\s*(?P<name>[A-Za-z0-9_-]+)\s*\)\s*"
    r"(?::\s*(?P<reason>.*?))?\s*(?=#\s*dslint-ok\(|$)")

#: directories never descended into when expanding path arguments
SKIP_DIRS = frozenset({"__pycache__", ".git", ".claude", "node_modules",
                       "tests", "examples"})


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str      # root-relative, '/'-separated
    line: int
    checker: str
    message: str

    @property
    def sort_key(self):
        return (self.path, self.line, self.checker, self.message)

    def human(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line,
                "checker": self.checker, "message": self.message}


class Checker:
    """Base class.  ``name`` is the suppression key; keep it kebab-case."""

    name: str = ""
    description: str = ""

    def applies(self, rel: str) -> bool:
        return True

    def visit(self, node: ast.AST, ctx: "FileContext") -> None:
        pass

    def end_file(self, ctx: "FileContext") -> None:
        pass

    def finish(self, run: "Runner") -> None:
        pass


class FileContext:
    """Per-file state shared by all checkers: source, AST, a parent map,
    an import-alias map, and the suppression table."""

    def __init__(self, run: "Runner", path: str, rel: str):
        self.run = run
        self.path = path
        self.rel = rel
        with open(path, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self._parents: Dict[ast.AST, ast.AST] = {}
        #: local name -> dotted origin ("time", "numpy", "time.perf_counter")
        self.imports: Dict[str, str] = {}
        #: line -> {checker names suppressed on that line}
        self.suppressions: Dict[int, Set[str]] = {}
        self._scan_suppressions()

    # ------------------------------------------------------------- parsing

    def parse(self) -> bool:
        try:
            self.tree = ast.parse(self.source, filename=self.path)
        except SyntaxError as e:
            self.run._add(Finding(self.rel, e.lineno or 1, "parse",
                                  f"unparseable: {e.msg}"))
            return False
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self._collect_imports()
        return True

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(node)

    def _collect_imports(self):
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module:
                mod = node.module.lstrip(".")  # normalize relative imports
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports[a.asname or a.name] = f"{mod}.{a.name}"

    def resolve_call(self, func: ast.AST) -> str:
        """Dotted origin of a call target, following import aliases:
        ``_time.time()`` -> ``time.time``; ``pc()`` after ``from time
        import perf_counter as pc`` -> ``time.perf_counter``."""
        parts: List[str] = []
        node = func
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return ""
        base = self.imports.get(node.id, node.id)
        parts.append(base)
        return ".".join(reversed(parts))

    # -------------------------------------------------------- suppressions

    def _scan_suppressions(self):
        if "dslint-ok" not in self.source:
            return  # skip tokenizing the vast majority of files
        # markers live in COMMENT tokens only — a docstring describing the
        # syntax must neither suppress anything nor read as malformed
        for i, line in self._comment_lines():
            if "dslint-ok" not in line:
                continue
            matched = False
            for m in SUPPRESS_RE.finditer(line):
                matched = True
                name, reason = m.group("name"), m.group("reason")
                if not reason:
                    self.run._add(Finding(
                        self.rel, i, "suppression",
                        f"dslint-ok({name}) without a reason — a suppression "
                        f"must record WHY: '# dslint-ok({name}): <why>'"))
                    continue
                if name not in self.run.checker_names:
                    self.run._add(Finding(
                        self.rel, i, "suppression",
                        f"dslint-ok({name}) names an unknown checker "
                        f"(known: {', '.join(sorted(self.run.checker_names))})"))
                    continue
                self.suppressions.setdefault(i, set()).add(name)
            if not matched:
                self.run._add(Finding(
                    self.rel, i, "suppression",
                    "malformed dslint-ok marker — expected "
                    "'# dslint-ok(<checker>): <reason>'"))

    def _comment_lines(self):
        """(lineno, comment_text) pairs; tolerant of tokenize errors (the
        parse checker reports real syntax problems separately)."""
        out = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.string))
        except (tokenize.TokenizeError, SyntaxError, IndentationError):
            pass
        return out

    def suppressed(self, line: int, checker: str) -> bool:
        return checker in self.suppressions.get(line, ())

    def report(self, checker: str, line: int, message: str) -> None:
        if self.suppressed(line, checker):
            self.run.suppressed_count += 1
            return
        self.run._add(Finding(self.rel, line, checker, message))


class Runner:
    """Collects files, runs every checker in one walk per file, then the
    cross-file ``finish`` phase.  Findings come out sorted — two identical
    runs produce byte-identical output (asserted in tier-1)."""

    def __init__(self, root: str, checkers: Sequence[Checker],
                 known_checker_names: Optional[Iterable[str]] = None):
        self.root = os.path.abspath(root)
        self.checkers = list(checkers)
        # suppression markers validate against the FULL registry, not just
        # the checkers selected for this run — a file annotated for checker
        # X must not read as "unknown checker" when only Y runs (the
        # atomic-write shim scans files carrying determinism markers)
        self.checker_names = set(known_checker_names or ()) \
            | {c.name for c in self.checkers} | {"suppression", "parse"}
        self.findings: List[Finding] = []
        self.files: List[str] = []          # rel paths scanned
        self.contexts: Dict[str, FileContext] = {}
        self.suppressed_count = 0

    def _add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def report(self, path: str, line: int, checker: str, message: str) -> None:
        """finish()-phase reporting; honors suppressions when the file was
        one of the scanned ones."""
        ctx = self.contexts.get(path)
        if ctx is not None:
            ctx.report(checker, line, message)
        else:
            self._add(Finding(path, line, checker, message))

    def run(self, paths: Sequence[str]) -> List[Finding]:
        for path in collect_files(paths, self.root):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            active = [c for c in self.checkers if c.applies(rel)]
            if not active:
                continue
            ctx = FileContext(self, path, rel)
            self.files.append(rel)
            self.contexts[rel] = ctx
            if not ctx.parse():
                continue
            for node in ast.walk(ctx.tree):
                for c in active:
                    c.visit(node, ctx)
            for c in active:
                c.end_file(ctx)
        for c in self.checkers:
            c.finish(self)
        self.findings.sort(key=lambda f: f.sort_key)
        return self.findings

    # -------------------------------------------------------------- output

    def to_json(self) -> str:
        return render_json([c.name for c in self.checkers], len(self.files),
                           self.suppressed_count, self.findings)

    def summary(self) -> str:
        return render_summary(len(self.files), self.suppressed_count,
                              self.findings)


def render_json(checker_names, files_scanned: int, suppressed: int,
                findings: Sequence[Finding]) -> str:
    """THE dslint json format — one renderer shared by the live Runner
    and the cache's replay (analysis/cache.py), so warm output is
    byte-identical to cold by construction, not by copy-paste."""
    doc = {
        "version": 1,
        "checkers": sorted(checker_names),
        "files_scanned": files_scanned,
        "suppressions_honored": suppressed,
        "findings": [f.to_dict() for f in findings],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def render_summary(files_scanned: int, suppressed: int,
                   findings: Sequence[Finding]) -> str:
    status = "FAIL" if findings else "OK"
    return (f"dslint: {status} — {len(findings)} finding(s), "
            f"{files_scanned} file(s) scanned, "
            f"{suppressed} suppression(s) honored")


def collect_files(paths: Iterable[str], root: str) -> List[str]:
    """Expand files/directories into a sorted list of .py files (sorted by
    root-relative path so the walk order — and therefore finding order and
    cross-file state accumulation — is platform-independent)."""
    out: Set[str] = set()
    for p in paths:
        p = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(p) and p.endswith(".py"):
            out.add(os.path.abspath(p))
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
                for fn in filenames:
                    if fn.endswith(".py"):
                        out.add(os.path.abspath(os.path.join(dirpath, fn)))
    return sorted(out, key=lambda f: os.path.relpath(f, root).replace(os.sep, "/"))
