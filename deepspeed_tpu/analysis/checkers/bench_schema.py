"""Checker ``bench-schema``: the committed ``BENCH_*.json`` artifacts
validate against scripts/check_bench_schema.py — registered here so ONE
``dslint`` invocation runs every contract the repo enforces (the original
tier-1 wiring, tests/unit/test_bench_schema.py, keeps running too).

This is the framework's one non-AST checker: it contributes nothing to
the per-file walk and does all its work in ``finish`` by delegating to the
schema script's ``validate_all`` (loaded standalone by path — stdlib-only,
same as the rest of dslint).
"""

import importlib.util
import os
import re

from ..core import Checker, Runner

_ERR_RE = re.compile(r"^(?P<name>BENCH_[\w.]+\.json)[:\s]")


class BenchSchemaChecker(Checker):
    name = "bench-schema"
    description = "committed BENCH_*.json artifacts match their schemas"

    def applies(self, rel: str) -> bool:
        return False  # finish-only: validates artifacts, not Python files

    def _script_path(self, run: Runner) -> str:
        local = os.path.join(run.root, "scripts", "check_bench_schema.py")
        if os.path.isfile(local):
            return local
        # fixture trees have no scripts/: fall back to the repo this
        # package lives in, so the checker still validates their BENCH files
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        return os.path.join(os.path.dirname(here), "scripts",
                            "check_bench_schema.py")

    def finish(self, run: Runner):
        script = self._script_path(run)
        if not os.path.isfile(script):
            return
        spec = importlib.util.spec_from_file_location("_dslint_bench_schema",
                                                      script)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        for err in mod.validate_all(run.root):
            m = _ERR_RE.match(err)
            path = m.group("name") if m else "BENCH"
            run.report(path, 1, self.name, err)
