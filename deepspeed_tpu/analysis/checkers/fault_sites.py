"""Checker ``fault-sites``: the injection-site taxonomy in
``resilience/fault_injection.INJECTION_SITES`` and the probes scattered
through the stack (``fi.check("<site>")``, ``writer_fault(site)``,
``retry_call(..., site=...)``) must agree in BOTH directions:

* a probe naming an unregistered site would raise ``ValueError`` the
  first time injection is armed — in the chaos drill, not in CI;
* a registered site with no production probe is a dead entry: a chaos
  plan arming it silently never fires, and docs/RESILIENCE.md lies.

The registry is read from the AST of whichever scanned file assigns
``INJECTION_SITES`` (no import of the package), so the checker also works
over test fixture trees carrying a miniature fault_injection.py.
"""

import ast
import re
from typing import Dict, List, Tuple

from ..core import Checker, FileContext, Runner

_SITE_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
_PROBE_FUNCS = ("check", "writer_fault")


class FaultSiteChecker(Checker):
    name = "fault-sites"
    description = ("inject-site literals registered in INJECTION_SITES; "
                   "every registered site probed in production")

    def __init__(self):
        #: site -> (rel, line) of its registry entry
        self.registry: Dict[str, Tuple[str, int]] = {}
        self.registry_file: str = ""
        #: (rel, line, site) for every probe literal outside the registry file
        self.uses: List[Tuple[str, int, str]] = []

    def visit(self, node, ctx: FileContext):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "INJECTION_SITES":
                    self.registry_file = ctx.rel
                    for const in ast.walk(node.value):
                        if isinstance(const, ast.Constant) \
                                and isinstance(const.value, str):
                            self.registry[const.value] = (ctx.rel, const.lineno)
            return
        if isinstance(node, ast.Call):
            self._collect_call(node, ctx)
        elif isinstance(node, ast.FunctionDef) or isinstance(node, ast.AsyncFunctionDef):
            self._collect_defaults(node, ctx)

    def _collect_call(self, node: ast.Call, ctx: FileContext):
        func = node.func
        fname = func.attr if isinstance(func, ast.Attribute) else \
            (func.id if isinstance(func, ast.Name) else "")
        if fname in _PROBE_FUNCS and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and _SITE_RE.match(arg.value):
                self._use(ctx, arg.lineno, arg.value)
        for kw in node.keywords:
            if kw.arg == "site" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str) and kw.value.value:
                self._use(ctx, kw.value.lineno, kw.value.value)

    def _collect_defaults(self, node, ctx: FileContext):
        # positional defaults right-align onto posonly + regular args
        # combined (ast.arguments.defaults spans both lists)
        allargs = node.args.posonlyargs + node.args.args
        pos_args = allargs[len(allargs) - len(node.args.defaults):]
        for a, d in list(zip(pos_args, node.args.defaults)) + \
                list(zip(node.args.kwonlyargs, node.args.kw_defaults)):
            if a.arg == "site" and isinstance(d, ast.Constant) \
                    and isinstance(d.value, str) and d.value:
                self._use(ctx, d.lineno, d.value)

    def _use(self, ctx: FileContext, line: int, site: str):
        # the registry file's own mentions (docstrings aside, its probes
        # reject rather than poll) are not production call sites
        if ctx.rel.endswith("fault_injection.py"):
            return
        self.uses.append((ctx.rel, line, site))

    def finish(self, run: Runner):
        if not self.registry:
            return  # no registry in the scan set: nothing to reconcile
        probed = set()
        for rel, line, site in self.uses:
            if site not in self.registry:
                run.report(rel, line, self.name,
                           f"injection site '{site}' is not in "
                           "INJECTION_SITES — arming it raises ValueError; "
                           "register it in resilience/fault_injection.py")
            else:
                probed.add(site)
        for site in sorted(self.registry):
            if site not in probed:
                rel, line = self.registry[site]
                run.report(rel, line, self.name,
                           f"registered injection site '{site}' has no "
                           "production probe — a chaos plan arming it "
                           "never fires")
