"""Checker ``crash-transparency-interproc``: the r11 crash-transparency
rule, lifted one call-hop through the project call graph.

The r11 checker guards every broad handler *inside* ``resilience/``,
``serving/`` and ``checkpoint/``.  What it structurally cannot see: a
crash-guarded region in scope calling a helper **outside** the scoped
directories (telemetry, monitor, utils) whose own ``except Exception``
swallows — the :class:`InjectedCrash` dies inside the helper and the
carefully-written ``except InjectedCrash: raise`` guard one frame up
never fires.  The simulated process death silently becomes a no-op and
the chaos suite tests nothing, which is exactly the laundering the r11
rule exists to forbid.

Rule: inside the scoped directories, any call **lexically inside a
``try`` that carries an InjectedCrash guard** (the author explicitly
demanded crash transparency there) resolving to a project function
defined *outside* the scoped directories whose body contains a broad
handler that neither re-raises nor is guarded (the r11 predicate,
shared via :mod:`..flow.callgraph`) is a finding at the call site.

Resolution is conservative on purpose (same-file bare names,
``self.method`` against the enclosing class, imported module-level
functions) — a missed resolution is a missed finding, never a false
one.  Helpers *inside* the scope are the plain checker's job; helpers
whose swallow is already suppressed with a reasoned marker in their own
file are respected here too.
"""

import ast

from ..core import Checker, FileContext, Runner
from ..flow import project_index
from .crash_transparency import SCOPE_SEGMENTS, _is_crash_guard


def _in_scope(rel: str) -> bool:
    r = "/" + rel
    return any(seg in r for seg in SCOPE_SEGMENTS)


def _guarded_region_calls(tnode: ast.Try):
    """Calls lexically inside ``tnode``'s body/else — the region its
    crash guard actually protects — without descending into nested
    crash-guarded trys (each is its own region, reported once)."""
    stack = list(tnode.body) + list(tnode.orelse)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Try) and \
                any(_is_crash_guard(h) for h in node.handlers):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


class CrashTransparencyInterprocChecker(Checker):
    name = "crash-transparency-interproc"
    description = ("helpers called from crash-guarded code must not "
                   "swallow InjectedCrash one hop down")

    def applies(self, rel: str) -> bool:
        return True  # out-of-scope files feed the call graph

    def finish(self, run: Runner) -> None:
        index = project_index(run)
        for rel in sorted(run.contexts):
            if not _in_scope(rel):
                continue
            ctx = run.contexts[rel]
            if ctx.tree is None:
                continue
            self._check_file(run, ctx, index)

    def _check_file(self, run: Runner, ctx: FileContext, index) -> None:
        # enclosing-function map comes from the index; guarded-try regions
        # from a single walk here
        funcs_here = index.by_rel.get(ctx.rel, ())

        def enclosing(node):
            best = None
            for f in funcs_here:
                if f.node.lineno <= node.lineno <= \
                        max(f.node.lineno,
                            getattr(f.node, "end_lineno", f.node.lineno)):
                    if best is None or f.node.lineno > best.node.lineno:
                        best = f
            return best

        for tnode in ast.walk(ctx.tree):
            if not isinstance(tnode, ast.Try):
                continue
            if not any(_is_crash_guard(h) for h in tnode.handlers):
                continue
            # only the BODY (and else) is under this guard's protection —
            # a crash raised from a handler or finally propagates past the
            # guard regardless; and nested crash-guarded trys are their
            # own protected regions (walked on their own iteration), so
            # skipping them here keeps every finding single-reported
            for call in _guarded_region_calls(tnode):
                caller = enclosing(call)
                for target in index.resolve(call, caller,
                                            imports=ctx.imports):
                    if _in_scope(target.rel) or not target.swallows:
                        continue
                    # respect a reasoned suppression at the helper's own
                    # handler line (the helper's author already decided)
                    helper_ctx = run.contexts.get(target.rel)
                    live = [
                        (ln, caught) for ln, caught in target.swallows
                        if helper_ctx is None
                        or not (helper_ctx.suppressed(ln, self.name)
                                or helper_ctx.suppressed(
                                    ln, "crash-transparency"))]
                    if not live:
                        continue
                    ln, caught = live[0]
                    ctx.report(
                        self.name, call.lineno,
                        f"call to {target.qualname}() ({target.rel}:{ln}) "
                        f"from a crash-guarded try: its '{caught}' absorbs "
                        "InjectedCrash one hop down — add the guard there "
                        "or re-raise")
