"""Checker ``kv-lifetime``: paged-KV acquisitions must reach a release or
an ownership transfer on EVERY path out of the acquiring function —
including the raise paths the chaos suites otherwise have to re-prove
leak-free by simulation ("zero refcount drift").

Acquire sites (the call's terminal name):

* ``allocate``       — a :class:`BlockedAllocator` page grant;
* ``export_prefix``  — a host-staged prefix :class:`KVSnapshot` (None =
  nothing staged: paths guarded by ``if x is None`` are exempt);
* ``begin_migration``— a paused-sequence :class:`KVExporter` (same
  Optional contract).

A path is *settled* when the tracked name passes through any of:

* a RELEASE/TRANSFER-named call (``free``/``release``/``release_tail``/
  ``truncate`` / ``adopt``/``register``/``import_*``/``put``/… —
  :mod:`..flow.callgraph`), or a project helper whose matching parameter
  is **consuming** (the call-graph fixpoint: helpers in
  ``serving/engine.py``, ``serving/kvtransfer/`` and ``fleet/router.py``
  release one hop — or several — down);
* a store into an attribute or subscript (``fr._kv_snapshot = snap``,
  ``self._migrations[fid] = m``), a plain alias (``x = snap``), or
  packing into a container literal (``m = {"exporter": exporter}``) —
  ownership moved beyond this checker's tracking, deliberately: a
  handoff, not a leak.  A value merely *derived* from the name
  (``n = len(pages)``) settles nothing;
* a ``return``/``yield`` carrying the name;
* an exit taken inside an ``if <name> is None`` / ``if not <name>``
  branch (the resource was never acquired on that path).

Passing the name to a *sink call that then raises* still settles the
path: ownership moved to the callee, whose own failure handling is
responsible (``import_snapshot`` frees what it allocated before
re-raising — checked on its own CFG).

Scope: ``serving/`` and ``inference/v2/`` — the paged-KV data plane.
An acquisition whose result is discarded outright (a bare expression
statement) is always a finding.
"""

import ast

from ..core import Checker, FileContext, Runner
from ..flow import build_cfg, call_name, project_index
from ..flow.callgraph import SINK_NAMES

SCOPE_SEGMENTS = ("/serving/", "/inference/v2/")
ACQUIRE_NAMES = frozenset({"allocate", "export_prefix", "begin_migration"})


def _assign_target_name(stmt: ast.AST, call: ast.Call):
    """The plain Name an acquire call's result is bound to, or a
    classification for the unbound cases."""
    if isinstance(stmt, ast.Assign) and stmt.value is call:
        if len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name):
            return stmt.targets[0].id
        if all(isinstance(t, (ast.Attribute, ast.Subscript))
               for t in stmt.targets):
            return "__stored__"       # stored straight into owner state
        return "__untracked__"        # tuple-unpack etc.: out of scope
    if isinstance(stmt, ast.Expr) and stmt.value is call:
        return "__discarded__"
    return "__untracked__"            # nested in a larger expression


def _contains_name(expr: ast.AST, name: str) -> bool:
    return any(isinstance(n, ast.Name) and n.id == name
               for n in ast.walk(expr))


def _is_name_or_slice(expr: ast.AST, name: str) -> bool:
    """The tracked resource ITSELF handed over: the bare name, a slice/
    element of it (``pages[off:off + cnt]``), or a starred spread —
    distinct from a value merely DERIVED from it (``len(pages)``), which
    transfers nothing."""
    if isinstance(expr, ast.Starred):
        expr = expr.value
    while isinstance(expr, ast.Subscript):
        expr = expr.value
    return isinstance(expr, ast.Name) and expr.id == name


def _is_packing(expr: ast.AST, name: str) -> bool:
    """The name packed into a fresh container literal (``m = {...,
    "exporter": exporter}``) — ownership moves into the new object."""
    if not isinstance(expr, (ast.Dict, ast.List, ast.Tuple, ast.Set)):
        return False
    return _contains_name(expr, name)


def _is_absence_test(test: ast.AST, name: str) -> bool:
    """``name is None`` / ``not name`` — the branch where the Optional
    acquire returned nothing."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.ops[0], ast.Is) \
            and isinstance(test.left, ast.Name) and test.left.id == name \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        return True
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name) \
            and test.operand.id == name:
        return True
    return False


class KVLifetimeChecker(Checker):
    name = "kv-lifetime"
    description = ("page/snapshot acquisitions reach a release or "
                   "ownership transfer on every path, raise paths included")

    def applies(self, rel: str) -> bool:
        # index every file (the call graph needs the helpers), report
        # only inside the scope segments
        return True

    def _in_scope(self, rel: str) -> bool:
        r = "/" + rel
        return any(seg in r for seg in SCOPE_SEGMENTS)

    def finish(self, run: Runner) -> None:
        index = project_index(run)
        for rel in sorted(run.contexts):
            if not self._in_scope(rel):
                continue
            ctx = run.contexts[rel]
            if ctx.tree is None:
                continue
            for info in index.by_rel.get(rel, ()):
                self._check_function(run, ctx, index, info)

    # ------------------------------------------------------------ per-func

    def _check_function(self, run: Runner, ctx: FileContext, index,
                        info) -> None:
        acquires = []
        for node in ast.walk(info.node):
            if isinstance(node, ast.Call) \
                    and call_name(node.func) in ACQUIRE_NAMES:
                acquires.append(node)
        # the definition of an acquire primitive is not a use of it
        acquires = [c for c in acquires
                    if call_name(c.func) != info.name]
        if not acquires:
            return
        cfg = build_cfg(info.node)
        # map call -> its CFG node (the node whose exprs contain the call)
        call_node = {}
        for n in cfg.nodes:
            for e in n.exprs:
                for sub in ast.walk(e):
                    if isinstance(sub, ast.Call):
                        call_node.setdefault(id(sub), n)
        for call in acquires:
            node = call_node.get(id(call))
            if node is None:
                continue  # in a nested def / comprehension: its own scope
            kind = call_name(call.func)
            stmt = node.stmt
            target = _assign_target_name(stmt, call) \
                if stmt is not None else "__untracked__"
            if target == "__discarded__":
                ctx.report(self.name, call.lineno,
                           f"result of {kind}() is discarded — the "
                           "acquired pages/snapshot can never be released")
                continue
            if target in ("__stored__", "__untracked__"):
                continue  # stored/handed off in the same statement
            kills = self._kill_nodes(ctx, cfg, index, info, target)
            escape = cfg.reach_escape(node.idx, kills)
            if escape is not None:
                where = "the function exit" if escape == "exit" \
                    else "an exception exit"
                ctx.report(self.name, call.lineno,
                           f"'{target}' acquired by {kind}() may leak: a "
                           f"path reaches {where} without a release, "
                           "ownership transfer, or None-guard")

    def _kill_nodes(self, ctx: FileContext, cfg, index, info,
                    name: str) -> set:
        imports = index.imports_by_rel.get(info.rel)
        kills = set()
        for n in cfg.nodes:
            if n.stmt is None:
                continue
            settled = False
            for e in n.exprs:
                for sub in ast.walk(e):
                    if isinstance(sub, ast.Call) and \
                            self._consuming_call(sub, name, index, info,
                                                 imports):
                        settled = True
            stmt = n.stmt
            if isinstance(stmt, ast.Assign):
                stored = any(isinstance(t, (ast.Attribute, ast.Subscript))
                             for t in stmt.targets)
                if (stored and _contains_name(stmt.value, name)) \
                        or isinstance(stmt.value, ast.Name) \
                        and stmt.value.id == name \
                        or _is_packing(stmt.value, name) \
                        or any(isinstance(t, ast.Name) and t.id == name
                               for t in stmt.targets):
                    # ownership moved: stored into owner state, aliased
                    # outright, packed into a container, or rebound —
                    # but a value merely DERIVED from the name
                    # (`n = len(pages)`) settles nothing
                    settled = True
            elif isinstance(stmt, ast.Return) and stmt.value is not None \
                    and _contains_name(stmt.value, name):
                settled = True
            elif isinstance(stmt, ast.Expr) \
                    and isinstance(stmt.value, (ast.Yield, ast.YieldFrom)) \
                    and stmt.value.value is not None \
                    and _contains_name(stmt.value.value, name):
                settled = True
            if not settled and self._under_absence_guard(ctx, stmt, name):
                settled = True
            if settled:
                kills.add(n.idx)
        return kills

    def _consuming_call(self, call: ast.Call, name: str, index, info,
                        imports) -> bool:
        # the resource itself must be an argument — a derived value
        # (`stats.append(len(pages))`) consumes nothing
        appears = any(
            _is_name_or_slice(a, name)
            for a in list(call.args) + [k.value for k in call.keywords])
        if not appears:
            return False
        if call_name(call.func) in SINK_NAMES:
            return True
        for target in index.resolve(call, info, imports=imports):
            pos = None
            kw = None
            for i, a in enumerate(call.args):
                if isinstance(a, ast.Name) and a.id == name:
                    pos = i
            for k in call.keywords:
                if k.arg is not None and isinstance(k.value, ast.Name) \
                        and k.value.id == name:
                    kw = k.arg
            params = target.params
            if params and params[0] == "self" \
                    and not isinstance(call.func, ast.Name):
                params = params[1:]
            if pos is not None and pos < len(params) \
                    and params[pos] in target.consuming:
                return True
            if kw is not None and kw in target.consuming:
                return True
        return False

    def _under_absence_guard(self, ctx: FileContext, stmt, name: str) -> bool:
        node = stmt
        while node is not None:
            parent = ctx.parent(node)
            if isinstance(parent, ast.If) and node in parent.body \
                    and _is_absence_test(parent.test, name):
                return True
            node = parent
        return False
