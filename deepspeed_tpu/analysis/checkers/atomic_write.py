"""Checker ``atomic-write``: durability-sensitive writers go through
``resilience/atomic_io.py`` (temp + fsync + rename) — the r8 lint
(scripts/check_atomic_writes.py), migrated into the framework; the old
script remains as a thin shim over this checker.

Inside the sensitive path set, every ``open(..., "w"/"wb"/"a"/"x"/"+")``
and every direct ``.savez``/``.savez_compressed`` must either use the
helper or justify itself.  Both the legacy ``# atomic-ok: <why>`` marker
and ``# dslint-ok(atomic-write): <why>`` are honored — the legacy marker
is grandfathered so r8's call-site annotations keep working unchanged.
"""

import ast
import fnmatch

from ..core import Checker, FileContext

SENSITIVE_GLOBS = [
    "deepspeed_tpu/checkpoint/*.py",
    "deepspeed_tpu/runtime/checkpoint_engine.py",
    "deepspeed_tpu/runtime/swap_tensor/*.py",
    "deepspeed_tpu/resilience/*.py",
    "scripts/bench_*.py",
    "scripts/aot_membudget.py",
    "bench.py",
    "bench_inference.py",
]

LEGACY_MARKER = "atomic-ok"
# '+' catches in-place mutation ('r+'/'rb+') — the same torn-file class
WRITE_MODES = ("w", "a", "x", "+")
FORBIDDEN_ATTRS = ("savez", "savez_compressed")


def _open_mode(call: ast.Call):
    """The mode of an ``open()`` call when statically known ('r' default)."""
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return None  # dynamic — not flagged


class AtomicWriteChecker(Checker):
    name = "atomic-write"
    description = ("bare writes on durability-sensitive paths must use "
                   "resilience.atomic_io")

    def applies(self, rel: str) -> bool:
        return any(fnmatch.fnmatch(rel, g) for g in SENSITIVE_GLOBS)

    def _legacy_allowed(self, ctx: FileContext, lineno: int) -> bool:
        return 0 < lineno <= len(ctx.lines) and LEGACY_MARKER in ctx.lines[lineno - 1]

    def visit(self, node, ctx: FileContext):
        if not isinstance(node, ast.Call):
            return
        func = node.func
        if isinstance(func, ast.Name) and func.id == "open":
            mode = _open_mode(node)
            if mode is not None and any(m in mode for m in WRITE_MODES) \
                    and not self._legacy_allowed(ctx, node.lineno):
                ctx.report(self.name, node.lineno,
                           f"bare open(..., {mode!r}) on a "
                           "durability-sensitive path — use "
                           "resilience.atomic_io (or justify with "
                           f"'# {LEGACY_MARKER}: <why>')")
        elif isinstance(func, ast.Attribute) and func.attr in FORBIDDEN_ATTRS \
                and not self._legacy_allowed(ctx, node.lineno):
            ctx.report(self.name, node.lineno,
                       f"direct .{func.attr}(...) on a durability-sensitive "
                       "path — use resilience.atomic_io.atomic_savez (or "
                       f"justify with '# {LEGACY_MARKER}: <why>')")
