"""Checker ``event-registry``: every monitor/telemetry event-name literal
in the package must be registered in ``telemetry/event_registry.py``, every
registered name must still have an emitter, and the generated event table
in docs/OBSERVABILITY.md must match :func:`render_event_table` — three
directions of drift, all fatal in tier-1.

Mechanics: any string constant matching ``<prefix>/<segment>[...]`` for
the known prefixes (resilience, serving, fleet, telemetry, monitor,
profiler, spec, migration, prefix, transport, slo, ctrl, recorder,
anatomy, kv, engine) is an event-name use — except statement-position strings
(docstrings) and the registry file itself.  f-string names
(``f"fleet/health/{state.value}"``) are validated by their literal head
against the registry's DYNAMIC prefix families.
"""

import ast
import importlib.util
import os
import re
from typing import Dict, List, Tuple

from ..core import Checker, FileContext, Runner, collect_files

EVENT_RE = re.compile(
    r"^(resilience|serving|fleet|telemetry|monitor|profiler|spec|migration"
    r"|prefix|transport|slo|ctrl|recorder|anatomy|kv|engine)"
    r"/[a-z0-9_]+(/[a-z0-9_]+)*$")
_PREFIXES = ("resilience/", "serving/", "fleet/", "telemetry/",
             "monitor/", "profiler/", "spec/", "migration/", "prefix/",
             "transport/", "slo/", "ctrl/", "recorder/", "anatomy/", "kv/",
             "engine/")
REGISTRY_REL = "telemetry/event_registry.py"


def _load_registry(path: str):
    spec = importlib.util.spec_from_file_location("_dslint_event_registry", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class EventRegistryChecker(Checker):
    name = "event-registry"
    description = ("event-name literals registered in "
                   "telemetry/event_registry.py; registered names emitted; "
                   "OBSERVABILITY.md table in sync")

    def __init__(self):
        self.literals: List[Tuple[str, int, str]] = []   # (rel, line, name)
        self.dynamic_heads: List[Tuple[str, int, str]] = []

    def applies(self, rel: str) -> bool:
        if rel.endswith(REGISTRY_REL):
            return False  # the registry's own entries are not emitter uses
        return True

    def visit(self, node, ctx: FileContext):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if EVENT_RE.match(node.value) \
                    and not isinstance(ctx.parent(node), ast.Expr):
                self.literals.append((ctx.rel, node.lineno, node.value))
        elif isinstance(node, ast.JoinedStr):
            head = ""
            if node.values and isinstance(node.values[0], ast.Constant) \
                    and isinstance(node.values[0].value, str):
                head = node.values[0].value
            if not head.startswith(_PREFIXES):
                return
            if all(isinstance(v, ast.Constant) for v in node.values):
                # an f-string with no placeholders is just a literal
                full = "".join(v.value for v in node.values)
                if EVENT_RE.match(full):
                    self.literals.append((ctx.rel, node.lineno, full))
                return
            self.dynamic_heads.append((ctx.rel, node.lineno, head))

    def finish(self, run: Runner):
        self.registry_path = os.path.join(run.root, "deepspeed_tpu",
                                          REGISTRY_REL)
        if not os.path.isfile(self.registry_path):
            return  # no registry in this tree: nothing to validate against
        reg = _load_registry(self.registry_path)
        names = frozenset(getattr(reg, "EVENTS", {}))
        prefixes = tuple(d["prefix"] for d in getattr(reg, "DYNAMIC", []))
        used = set()
        for rel, line, name in self.literals:
            # literals are validated STRICTLY against EVENTS: the DYNAMIC
            # prefix families only legitimize f-strings, otherwise one
            # broad prefix would waive its whole namespace
            if name in names:
                used.add(name)
            else:
                run.report(rel, line, self.name,
                           f"event name '{name}' is not registered in "
                           f"{REGISTRY_REL} — add it (and regenerate the "
                           "OBSERVABILITY.md table)")
        for rel, line, head in self.dynamic_heads:
            if not any(head.startswith(p) or p.startswith(head)
                       for p in prefixes):
                run.report(rel, line, self.name,
                           f"dynamic event name f\"{head}...\" matches no "
                           f"DYNAMIC prefix family in {REGISTRY_REL}")
        if self._scanned_full_scope(run):
            self._check_unemitted(run, reg, names, used)
        self._check_doc_sync(run, reg)

    def _scanned_full_scope(self, run: Runner) -> bool:
        """'No emitter' is only decidable when every potential emitter was
        scanned — on a partial invocation (`dslint.py path/to/file.py`)
        absent emitters are an artifact of scope, not dead registry
        entries, so that direction is skipped."""
        pkg = os.path.join(run.root, "deepspeed_tpu")
        if not os.path.isdir(pkg):
            return True  # fixture trees: whatever was given IS the scope
        expected = collect_files([pkg], run.root)
        scanned = set(run.contexts)
        return all(
            os.path.relpath(f, run.root).replace(os.sep, "/") in scanned
            for f in expected
            # the registry itself is applies()-excluded, never scanned
            if not f.endswith(REGISTRY_REL))

    def _check_unemitted(self, run: Runner, reg, names, used):
        reg_rel = "deepspeed_tpu/" + REGISTRY_REL
        src_lines = []
        try:
            with open(self.registry_path, encoding="utf-8") as f:
                src_lines = f.read().splitlines()
        except OSError:
            pass

        def line_of(name: str) -> int:
            quoted = f'"{name}"'
            for i, l in enumerate(src_lines, start=1):
                if quoted in l:
                    return i
            return 1

        for name in sorted(names - used):
            run.report(reg_rel, line_of(name), self.name,
                       f"registered event '{name}' has no emitter in the "
                       "scanned tree — dead registry entry (or the emitter "
                       "moved out of scan scope)")

    def _check_doc_sync(self, run: Runner, reg):
        render = getattr(reg, "render_event_table", None)
        extract = getattr(reg, "extract_doc_block", None)
        if render is None or extract is None:
            return  # miniature fixture registries skip the doc contract
        doc_path = os.path.join(run.root, "docs", "OBSERVABILITY.md")
        if not os.path.isfile(doc_path):
            return
        with open(doc_path, encoding="utf-8") as f:
            text = f.read()
        block = extract(text)
        doc_rel = "docs/OBSERVABILITY.md"
        if block is None:
            run.report(doc_rel, 1, self.name,
                       "event-table markers missing — the event table must "
                       f"be generated from {REGISTRY_REL}")
        elif block != render():
            run.report(doc_rel, 1, self.name,
                       "committed event table differs from "
                       f"render_event_table() — run `python deepspeed_tpu/"
                       "telemetry/event_registry.py --sync docs/OBSERVABILITY.md`")
