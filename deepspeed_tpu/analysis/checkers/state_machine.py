"""Checker ``state-machine``: the serving stack's lifecycle enums must
declare their transition tables, every transition site must agree with
the declared table, and the generated ``docs/STATE_MACHINES.md`` must
match what the AST actually declares — the same drift-as-finding
contract as the r11 event table.

What counts as a *state machine* here:

* an ``enum.Enum`` subclass with a **declared transition table** — a
  dict literal whose keys are ``Enum.MEMBER`` attributes and whose
  values are sets of members of the same enum (``_ALLOWED`` in
  ``serving/request.py`` is the canonical shape); or
* an enum that appears at a **transition site** — a ``to(...)`` /
  ``_to(...)`` call taking an ``Enum.MEMBER`` argument, or a literal
  ``<obj>.state = Enum.MEMBER`` store — whether or not anyone declared
  a table for it yet (that omission is finding #1 below).

Rules, each its own finding class:

1. *no declared table* — an enum with transition sites but no table
   (``LeaseState`` before r17: ``FleetHealthView._to`` accepted any
   hop);
2. *table exhaustiveness* — every member is a key (terminals map to the
   empty set), and keys/values name only real members;
3. *direct state write* — a literal ``.state = Enum.MEMBER`` store
   anywhere but a ``to``/``_to`` transition method (or ``__init__`` /
   ``__post_init__`` stamping the initial state) bypasses table
   validation (``router.py``'s ``fr.state = FleetState.…`` sites before
   r17);
4. *undeclared transition target* — a ``to``/``_to`` call whose literal
   target member appears in no table entry's allowed set: statically
   unreachable per the declared machine;
5. *non-exhaustive dispatch* — an ``if``/``elif`` chain whose arms are
   all ``<subject> is Enum.MEMBER`` tests (≥2 of them, one subject, no
   ``else``) that covers only part of the enum: the unhandled members
   fall through silently;
6. *doc drift* — ``docs/STATE_MACHINES.md``'s generated block differs
   from :func:`render_state_table` over the scanned tree (full-repo
   scans only; regenerate with ``scripts/dslint.py
   --sync-state-machines``).

Graceful-degradation **ladders** (``RUNGS = ("normal", …)`` in
``fleet/autoscale.py``) are extracted into the doc table too — their
transition rule (moves of ±1 rung) is structural, so only the doc-sync
direction applies to them.
"""

import ast
import os
from typing import Dict, List, Optional, Tuple

from ..core import Checker, FileContext, Runner, collect_files

ENUM_BASES = {"Enum", "IntEnum", "StrEnum", "Flag", "IntFlag"}
TRANSITION_METHODS = {"to", "_to"}
INIT_METHODS = {"to", "_to", "__init__", "__post_init__"}
DOC_REL = "docs/STATE_MACHINES.md"
DOC_BEGIN = "<!-- dslint:state-machines:begin -->"
DOC_END = "<!-- dslint:state-machines:end -->"

DOC_HEADER = """# State machines (generated)

Declared lifecycle state machines of the serving stack, extracted from
the AST by the ``state-machine`` flow checker (docs/ANALYSIS.md).  Do
not edit the table block by hand — regenerate with::

    python scripts/dslint.py --sync-state-machines

Drift between this file and the declared tables is a tier-1 dslint
finding, exactly like the OBSERVABILITY.md event table.  ``FleetHealthView``
pairs its ``LeaseState`` machine with a per-replica **dispatch epoch**
that bumps on every ALIVE/SUSPECT → DEAD lease expiry — the fencing
token that makes a zombie's late completions discardable.
"""


class _Machine:
    def __init__(self, name: str, rel: str, lineno: int,
                 members: List[str]):
        self.name = name
        self.rel = rel
        self.lineno = lineno
        self.members = members            # declaration order
        self.table: Optional[Dict[str, List[str]]] = None
        self.table_rel: Optional[str] = None
        self.table_line: int = 0


def _enum_bases(cls: ast.ClassDef) -> bool:
    for b in cls.bases:
        if isinstance(b, ast.Name) and b.id in ENUM_BASES:
            return True
        if isinstance(b, ast.Attribute) and b.attr in ENUM_BASES:
            return True
    return False


def _enum_members(cls: ast.ClassDef) -> List[str]:
    out = []
    for stmt in cls.body:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name) \
                and not stmt.targets[0].id.startswith("_"):
            out.append(stmt.targets[0].id)
    return out


def _member_ref(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``Enum.MEMBER`` -> ("Enum", "MEMBER")."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        return (node.value.id, node.attr)
    return None


def _parse_table(value: ast.AST) -> Optional[Tuple[str, Dict[str, List[str]],
                                                   List[Tuple[str, str]]]]:
    """A transition-table dict literal -> (enum name, {member: targets},
    [(enum, member) refs that named a foreign/unknown enum]) or None."""
    if not isinstance(value, ast.Dict) or not value.keys:
        return None
    enum_name = None
    table: Dict[str, List[str]] = {}
    refs: List[Tuple[str, str]] = []
    for k, v in zip(value.keys, value.values):
        ref = _member_ref(k)
        if ref is None:
            return None
        refs.append(ref)
        if enum_name is None:
            enum_name = ref[0]
        if isinstance(v, ast.Set):
            targets = []
            for e in v.elts:
                r = _member_ref(e)
                if r is None:
                    return None
                refs.append(r)
                targets.append(r[1])
        elif isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                and v.func.id == "set" and not v.args:
            targets = []
        else:
            return None
        table[ref[1]] = targets
    if any(r[0] != enum_name for r in refs):
        return None
    return enum_name, table, refs


class StateMachineChecker(Checker):
    name = "state-machine"
    description = ("declared transition tables are exhaustive, every "
                   "transition site agrees with them, STATE_MACHINES.md "
                   "in sync")

    def __init__(self):
        self.machines: Dict[str, _Machine] = {}
        self.ladders: List[Tuple[str, str, int, List[str]]] = []
        #: enum name -> every (rel, lineno) that declared it; a name
        #: declared in two files cannot be validated by bare-name keying
        self._decls: Dict[str, List[Tuple[str, int]]] = {}

    def applies(self, rel: str) -> bool:
        return True

    # ------------------------------------------------------------- extract

    def _extract(self, run: Runner) -> None:
        for rel in sorted(run.contexts):
            ctx = run.contexts[rel]
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef) and _enum_bases(node):
                    members = _enum_members(node)
                    if members:
                        self._decls.setdefault(node.name, []).append(
                            (rel, node.lineno))
                        if node.name not in self.machines:
                            self.machines[node.name] = _Machine(
                                node.name, rel, node.lineno, members)
                elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if isinstance(t, ast.Name) and t.id == "RUNGS" \
                            and isinstance(node.value, (ast.Tuple, ast.List)) \
                            and node.value.elts \
                            and all(isinstance(e, ast.Constant)
                                    and isinstance(e.value, str)
                                    for e in node.value.elts):
                        self.ladders.append(
                            (t.id, rel, node.lineno,
                             [e.value for e in node.value.elts]))
        # a name declared in several files cannot be validated by bare-
        # name keying: drop it from the machine set (no wrong-member
        # false findings) and flag it below IF a table claims it
        ambiguous = {name for name, decls in self._decls.items()
                     if len({r for r, _ in decls}) > 1}
        for name in ambiguous:
            self.machines.pop(name, None)
        # second pass: tables (enums may be declared in another file)
        for rel in sorted(run.contexts):
            ctx = run.contexts[rel]
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                parsed = _parse_table(node.value)
                if parsed is None:
                    continue
                enum_name, table, refs = parsed
                if enum_name in ambiguous:
                    rels = sorted({r for r, _ in self._decls[enum_name]})
                    run.report(rel, node.lineno, self.name,
                               f"transition table for {enum_name} cannot "
                               f"be validated: the enum name is declared "
                               f"in multiple files ({', '.join(rels)}) — "
                               "rename one so the tables key unambiguously")
                    continue
                m = self.machines.get(enum_name)
                if m is None:
                    continue  # a dict of someone else's constants
                if m.table is None:
                    m.table = table
                    m.table_rel = rel
                    m.table_line = node.lineno
                for ename, member in refs:
                    if member not in m.members:
                        run.report(rel, node.lineno, self.name,
                                   f"transition table for {enum_name} names "
                                   f"unknown member '{member}' (members: "
                                   f"{', '.join(m.members)})")
        self.ladders.sort()

    # -------------------------------------------------------------- finish

    def finish(self, run: Runner) -> None:
        self._extract(run)
        sites: Dict[str, Tuple[str, int]] = {}  # enum -> first site
        for rel in sorted(run.contexts):
            ctx = run.contexts[rel]
            if ctx.tree is None:
                continue
            self._check_file(run, ctx, sites)
        # rule 1: transitions without a declared table
        for enum_name in sorted(sites):
            m = self.machines.get(enum_name)
            if m is not None and m.table is None:
                rel, line = sites[enum_name]
                run.report(rel, line, self.name,
                           f"{enum_name} has transition sites but no "
                           "declared transition table — declare an "
                           "_ALLOWED-style dict next to the enum (pattern: "
                           "serving/request.py) and validate in the "
                           "transition method")
        # rule 2: table exhaustiveness
        for name in sorted(self.machines):
            m = self.machines[name]
            if m.table is None:
                continue
            missing = [mem for mem in m.members if mem not in m.table]
            if missing:
                run.report(m.table_rel, m.table_line, self.name,
                           f"transition table for {name} is missing "
                           f"member(s): {', '.join(missing)} (terminals "
                           "map to the empty set, never go missing)")
        self._check_doc_sync(run)

    # ------------------------------------------------------------ per-file

    def _check_file(self, run: Runner, ctx: FileContext,
                    sites: Dict[str, Tuple[str, int]]) -> None:
        func_stack: List[str] = []

        def record_site(enum_name: str, line: int) -> None:
            if enum_name not in sites:
                sites[enum_name] = (ctx.rel, line)

        def walk(node, funcs):
            for child in ast.iter_child_nodes(node):
                inner = funcs
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    inner = funcs + [child.name]
                self._visit_node(ctx, child, inner, record_site)
                walk(child, inner)

        walk(ctx.tree, func_stack)

    def _visit_node(self, ctx: FileContext, node, funcs, record_site) -> None:
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in TRANSITION_METHODS:
            for a in list(node.args) + [k.value for k in node.keywords]:
                ref = _member_ref(a)
                if ref is None or ref[0] not in self.machines:
                    continue
                m = self.machines[ref[0]]
                if ref[1] not in m.members:
                    continue
                record_site(ref[0], node.lineno)
                if m.table is not None:
                    reachable = {t for targets in m.table.values()
                                 for t in targets}
                    if ref[1] not in reachable:
                        ctx.report(self.name, node.lineno,
                                   f"transition to {ref[0]}.{ref[1]} is "
                                   "declared unreachable: no table entry "
                                   "allows it as a target")
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and t.attr == "state":
                    ref = _member_ref(node.value)
                    if ref is None or ref[0] not in self.machines \
                            or ref[1] not in self.machines[ref[0]].members:
                        continue
                    record_site(ref[0], node.lineno)
                    if not (funcs and funcs[-1] in INIT_METHODS):
                        ctx.report(
                            self.name, node.lineno,
                            f"direct state write .state = {ref[0]}."
                            f"{ref[1]} bypasses the validated transition "
                            "method — route it through to()/_to() so the "
                            "declared table is enforced")
        elif isinstance(node, ast.If):
            self._check_dispatch_chain(ctx, node)

    def _check_dispatch_chain(self, ctx: FileContext, node: ast.If) -> None:
        parent = ctx.parent(node)
        if isinstance(parent, ast.If) and parent.orelse == [node]:
            return  # not the chain head
        subject = None
        enum_name = None
        covered: List[str] = []
        cur = node
        while True:
            test = cur.test
            ok = (isinstance(test, ast.Compare) and len(test.ops) == 1
                  and isinstance(test.ops[0], (ast.Is, ast.Eq)))
            ref = _member_ref(test.comparators[0]) if ok else None
            if ref is None or ref[0] not in self.machines \
                    or ref[1] not in self.machines[ref[0]].members:
                return
            subj = ast.dump(test.left)
            if subject is None:
                subject, enum_name = subj, ref[0]
            elif subj != subject or ref[0] != enum_name:
                return
            covered.append(ref[1])
            if len(cur.orelse) == 1 and isinstance(cur.orelse[0], ast.If):
                cur = cur.orelse[0]
                continue
            if cur.orelse:
                return  # has a final else: exhaustive by construction
            break
        if len(covered) < 2:
            return
        members = self.machines[enum_name].members
        missing = [m for m in members if m not in covered]
        if missing:
            ctx.report(self.name, node.lineno,
                       f"state dispatch over {enum_name} handles "
                       f"{', '.join(covered)} but not "
                       f"{', '.join(missing)} — add the missing arm(s) "
                       "or a final else")

    # ------------------------------------------------------------ doc sync

    def render_state_table(self) -> str:
        lines = [DOC_BEGIN, ""]
        for name in sorted(self.machines,
                           key=lambda n: (self.machines[n].rel, n)):
            m = self.machines[name]
            if m.table is None:
                continue
            lines.append(f"### `{name}` — `{m.rel}`")
            lines.append("")
            lines.append("| from | allowed to |")
            lines.append("|---|---|")
            for mem in m.members:
                targets = m.table.get(mem)
                if targets is None:
                    cell = "*(missing from table)*"
                elif not targets:
                    cell = "— *(terminal)*"
                else:
                    ordered = [t for t in m.members if t in targets]
                    cell = ", ".join(f"`{t}`" for t in ordered)
                lines.append(f"| `{mem}` | {cell} |")
            lines.append("")
        for name, rel, _line, rungs in self.ladders:
            lines.append(f"### ladder `{name}` — `{rel}`")
            lines.append("")
            lines.append(" → ".join(f"`{i} {r}`"
                                    for i, r in enumerate(rungs)))
            lines.append("")
            lines.append("Moves are ±1 rung per update (no skipping), "
                         "symmetric up and down.")
            lines.append("")
        lines.append(DOC_END)
        return "\n".join(lines) + "\n"

    @staticmethod
    def extract_doc_block(text: str) -> Optional[str]:
        i = text.find(DOC_BEGIN)
        j = text.find(DOC_END)
        if i < 0 or j < 0 or j < i:
            return None
        return text[i:j + len(DOC_END)] + "\n"

    def sync_doc(self, root: str) -> str:
        """Write the generated doc; returns the path (dslint
        --sync-state-machines)."""
        path = os.path.join(root, DOC_REL)
        content = DOC_HEADER + "\n" + self.render_state_table()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(content)
        return path

    def _scanned_full_scope(self, run: Runner) -> bool:
        pkg = os.path.join(run.root, "deepspeed_tpu")
        if not os.path.isdir(pkg):
            return True
        expected = collect_files([pkg], run.root)
        scanned = set(run.contexts)
        return all(
            os.path.relpath(f, run.root).replace(os.sep, "/") in scanned
            for f in expected)

    def _check_doc_sync(self, run: Runner) -> None:
        doc_path = os.path.join(run.root, DOC_REL)
        if not os.path.isfile(doc_path):
            return  # fixture trees / pre-sync repos: nothing to drift
        if not self._scanned_full_scope(run):
            return  # partial scan: absent machines are a scope artifact
        with open(doc_path, encoding="utf-8") as f:
            text = f.read()
        block = self.extract_doc_block(text)
        if block is None:
            run.report(DOC_REL, 1, self.name,
                       "state-machine table markers missing — regenerate "
                       "with `python scripts/dslint.py "
                       "--sync-state-machines`")
        elif block != self.render_state_table():
            run.report(DOC_REL, 1, self.name,
                       "committed state-machine table differs from the "
                       "declared transition tables — run `python "
                       "scripts/dslint.py --sync-state-machines`")
