"""Checker registry.  Add a checker: subclass ``core.Checker`` in a module
here, then list it in ``ALL`` (docs/ANALYSIS.md walks through an example)."""

from .atomic_write import AtomicWriteChecker
from .bench_schema import BenchSchemaChecker
from .crash_transparency import CrashTransparencyChecker
from .crash_transparency_interproc import CrashTransparencyInterprocChecker
from .determinism import DeterminismChecker
from .event_registry import EventRegistryChecker
from .fault_sites import FaultSiteChecker
from .kv_lifetime import KVLifetimeChecker
from .state_machine import StateMachineChecker

ALL = (
    DeterminismChecker,
    CrashTransparencyChecker,
    CrashTransparencyInterprocChecker,
    FaultSiteChecker,
    EventRegistryChecker,
    AtomicWriteChecker,
    BenchSchemaChecker,
    KVLifetimeChecker,
    StateMachineChecker,
)


def all_checkers():
    return [cls() for cls in ALL]


def checker_names():
    return [cls.name for cls in ALL]
