"""Checker registry.  Add a checker: subclass ``core.Checker`` in a module
here, then list it in ``ALL`` (docs/ANALYSIS.md walks through an example)."""

from .atomic_write import AtomicWriteChecker
from .bench_schema import BenchSchemaChecker
from .crash_transparency import CrashTransparencyChecker
from .determinism import DeterminismChecker
from .event_registry import EventRegistryChecker
from .fault_sites import FaultSiteChecker

ALL = (
    DeterminismChecker,
    CrashTransparencyChecker,
    FaultSiteChecker,
    EventRegistryChecker,
    AtomicWriteChecker,
    BenchSchemaChecker,
)


def all_checkers():
    return [cls() for cls in ALL]


def checker_names():
    return [cls.name for cls in ALL]
