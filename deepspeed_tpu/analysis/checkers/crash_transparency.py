"""Checker ``crash-transparency``: the chaos contract
(resilience/fault_injection.py) is that :class:`InjectedCrash` — simulated
process death — is NEVER absorbed: not by retry loops, not by
"observability must never break the operation" shields, not by per-request
error isolation.  A chaos test that kills a replica mid-monitor-forward
must see the crash, or the kill silently becomes a no-op and the whole
fault-injection suite tests nothing.

Rule: inside ``resilience/``, ``serving/`` and ``checkpoint/``, every
broad handler (bare ``except``, ``except Exception``, ``except
BaseException``) must satisfy one of:

* a PRECEDING handler in the same ``try`` is exactly
  ``except InjectedCrash: raise`` (the guard pattern,
  serving/fleet/pool.py); or
* the handler itself unconditionally re-raises: its last top-level
  statement is a bare ``raise`` AND no statement anywhere in the handler
  can exit before reaching it (``return``/``break``/``continue``, or a
  ``raise`` of a *different* exception — ``raise OSError(...) from e``
  launders the crash into a retryable type, and a conditional early exit
  would swallow it on that path); or
* a ``# dslint-ok(crash-transparency): <why>`` suppression on the
  ``except`` line.
"""

import ast

from ..core import Checker, FileContext

SCOPE_SEGMENTS = ("/resilience/", "/serving/", "/checkpoint/")
_BROAD_NAMES = ("Exception", "BaseException")


def _type_name(node) -> str:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return ""


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if _type_name(t) in _BROAD_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(_type_name(e) in _BROAD_NAMES for e in t.elts)
    return False


def _is_crash_guard(handler: ast.ExceptHandler) -> bool:
    """``except InjectedCrash: raise`` — nothing more, nothing less."""
    if _type_name(handler.type) != "InjectedCrash":
        return False
    return (len(handler.body) == 1
            and isinstance(handler.body[0], ast.Raise)
            and handler.body[0].exc is None)


def _reraises(handler: ast.ExceptHandler) -> bool:
    last = handler.body[-1]
    if not (isinstance(last, ast.Raise) and last.exc is None):
        return False
    # the trailing bare raise must be unavoidable: a return/break/continue
    # nested in the handler (e.g. `if is_transient(e): return None`) or a
    # raise of a DIFFERENT exception (`raise Retryable() from e` — the
    # laundering the module docstring rejects) opens a path that absorbs
    # InjectedCrash, so the handler doesn't count as a re-raise
    # (nested def/lambda bodies are separate scopes and don't exit this one)
    return not any(_has_early_exit(stmt) for stmt in handler.body[:-1])


def _has_early_exit(node: ast.AST, in_loop: bool = False) -> bool:
    if isinstance(node, ast.Return):
        return True
    if isinstance(node, ast.Raise):
        return node.exc is not None  # raising a different exception launders
    if isinstance(node, (ast.Break, ast.Continue)):
        return not in_loop  # inside a handler-local loop they stay put
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return False  # separate scope — its exits can't leave the handler
    if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
        return any(_has_early_exit(c, in_loop=True)
                   for c in ast.iter_child_nodes(node))
    return any(_has_early_exit(c, in_loop) for c in ast.iter_child_nodes(node))


class CrashTransparencyChecker(Checker):
    name = "crash-transparency"
    description = ("broad except in resilience/serving/checkpoint must "
                   "re-raise InjectedCrash first")

    def applies(self, rel: str) -> bool:
        r = "/" + rel
        return any(seg in r for seg in SCOPE_SEGMENTS)

    def visit(self, node, ctx: FileContext):
        if not isinstance(node, ast.Try):
            return
        guarded = False
        for handler in node.handlers:
            if _is_crash_guard(handler):
                guarded = True
                continue
            if not _is_broad(handler):
                continue
            if guarded or _reraises(handler):
                continue
            caught = "bare except" if handler.type is None else \
                f"except {ast.unparse(handler.type)}"
            ctx.report(self.name, handler.lineno,
                       f"{caught} absorbs InjectedCrash — add "
                       "'except InjectedCrash: raise' before it (guard "
                       "pattern, serving/fleet/pool.py) or re-raise")
