"""Checker ``determinism``: the bit-reproducibility arc (VirtualClock
traces, FleetSimulator byte-identical benches, seeded chaos) only holds if
nondeterminism can't leak in through the three classic side doors:

1. **Wall-clock reads** (``time.time``/``monotonic``/``perf_counter``,
   ``datetime.now``) anywhere outside the allowlisted pluggable-clock
   modules.  Everything else must take a clock object (serving/clock.py)
   or a tracer (telemetry/trace.py) so tests can pin time.
2. **Filesystem enumeration order** — ``os.listdir``/``glob.glob``
   results are OS/filesystem-order unless sorted; feeding them into
   selection or iteration makes behaviour differ across machines (the
   r11 live hit: checkpoint tag scanning for newest-valid-tag fallback).
   Order-independent sinks (``sorted``/``set``/``len``/membership) pass.
3. **Global-RNG randomness** — legacy ``random.*`` / ``np.random.*``
   module-level functions share hidden interpreter-global state; any
   import order change reshuffles every downstream draw.  Seeded
   instances (``random.Random(seed)``, ``np.random.default_rng(seed)``,
   ``jax.random``) pass.
"""

import ast

from ..core import Checker, FileContext

#: modules allowed to read the wall clock: the pluggable-clock primitives
#: everything else is supposed to depend on
CLOCK_MODULE_SUFFIXES = (
    "deepspeed_tpu/serving/clock.py",
    "deepspeed_tpu/telemetry/trace.py",
    "deepspeed_tpu/utils/timer.py",
)

WALL_CLOCK = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})

FS_ENUM = frozenset({"os.listdir", "os.scandir", "glob.glob", "glob.iglob"})

#: legacy module-level functions drawing from the hidden global RNG
GLOBAL_RANDOM = frozenset({
    "random.random", "random.randint", "random.randrange", "random.choice",
    "random.choices", "random.shuffle", "random.sample", "random.uniform",
    "random.gauss", "random.normalvariate", "random.seed",
    "numpy.random.rand", "numpy.random.randn", "numpy.random.randint",
    "numpy.random.random", "numpy.random.random_sample", "numpy.random.ranf",
    "numpy.random.sample", "numpy.random.choice", "numpy.random.shuffle",
    "numpy.random.permutation", "numpy.random.uniform", "numpy.random.normal",
    "numpy.random.standard_normal", "numpy.random.seed",
})

#: wrappers that make enumeration order irrelevant
_ORDER_INDEPENDENT_CALLS = frozenset({"sorted", "set", "frozenset", "len"})


class DeterminismChecker(Checker):
    name = "determinism"
    description = ("wall-clock reads outside clock modules, unsorted "
                   "filesystem enumeration, global-RNG randomness")

    def applies(self, rel: str) -> bool:
        # tests may freely read clocks and draw randomness; the contract
        # binds production code (and the committed bench scripts)
        return "tests/" not in rel and not rel.startswith("tests")

    def visit(self, node, ctx: FileContext):
        if not isinstance(node, ast.Call):
            return
        target = ctx.resolve_call(node.func)
        if not target:
            return
        if target in WALL_CLOCK:
            if not any(ctx.rel.endswith(s) for s in CLOCK_MODULE_SUFFIXES):
                ctx.report(self.name, node.lineno,
                           f"wall-clock read {target}() outside the clock "
                           "modules — take a pluggable clock "
                           "(serving/clock.py) so tests can pin time")
        elif target in FS_ENUM:
            if not self._order_independent(node, ctx):
                ctx.report(self.name, node.lineno,
                           f"{target}() order is filesystem-dependent — wrap "
                           "in sorted(...) before selecting or iterating")
        elif target in GLOBAL_RANDOM:
            ctx.report(self.name, node.lineno,
                       f"{target}() draws from the hidden global RNG — use a "
                       "seeded instance (random.Random(seed) / "
                       "np.random.default_rng(seed))")

    def _order_independent(self, node: ast.Call, ctx: FileContext) -> bool:
        """Is the enumeration's immediate sink order-independent?"""
        p = ctx.parent(node)
        if isinstance(p, ast.Call) and isinstance(p.func, ast.Name) \
                and p.func.id in _ORDER_INDEPENDENT_CALLS:
            return True
        if isinstance(p, ast.Compare):
            # only membership (`x in os.listdir(d)`) ignores order; `==`/
            # `<` on the listing itself compares in enumeration order
            for op, comparator in zip(p.ops, p.comparators):
                if comparator is node:
                    return isinstance(op, (ast.In, ast.NotIn))
            return False  # node is p.left: order-sensitive
        if isinstance(p, ast.comprehension) and p.iter is node:
            comp = ctx.parent(p)
            # set/dict comprehensions erase order; list/genexp keep it
            return isinstance(comp, (ast.SetComp, ast.DictComp))
        return False
