"""dslint incremental cache: findings keyed on content hashes.

A warm full-repo run costs one sha256 sweep (~tens of ms) instead of a
parse + 9-checker walk of every file (~seconds).  Correctness stance:
several checkers are **cross-file** (event/fault-site registries, the
call graph, the state-machine tables, doc sync), so a single changed
file can move findings in *other* files — the cache therefore replays a
stored run only when EVERY input matches:

* the selected checker set,
* the resolved file list and each file's content hash (per-file keyed,
  exactly as the findings are stored),
* the analysis package's own sources (editing a checker invalidates
  everything it ever reported).

Anything else is a full re-run that refreshes the store.  Replayed
output is byte-identical to the live run's ``--json`` (asserted in
tier-1): findings are stored per file plus a cross-file remainder
(docs/BENCH artifacts) and re-sorted through the same ``Finding`` path.

Persistence is ``.dslint_cache/cache.json`` under the repo root,
published with the same temp + fsync + atomic-rename discipline as
``resilience/atomic_io.py`` — re-implemented here in ~10 lines because
``analysis/`` must stay importable without the deepspeed_tpu package
(the no-jax load is what keeps dslint inside its runtime budget).  A
torn or unreadable cache file is treated as a miss, never an error.
``--no-cache`` bypasses reads and writes entirely.
"""

import hashlib
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .core import Finding, render_json, render_summary

CACHE_DIR = ".dslint_cache"
CACHE_NAME = "cache.json"
VERSION = 1
#: distinct (checker set x file set) run records retained, LRU by use
MAX_RUNS = 8


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write_text(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def analysis_sources_hash() -> str:
    """Hash of every .py in the analysis package itself — a checker edit
    must invalidate every cached verdict it produced."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha256()
    names = []
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                names.append(os.path.join(dirpath, fn))
    for path in sorted(names):
        h.update(os.path.relpath(path, pkg).encode())
        h.update(_sha256_file(path).encode())
    return h.hexdigest()


class DslintCache:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.path = os.path.join(self.root, CACHE_DIR, CACHE_NAME)

    # ------------------------------------------------------------- hashing

    def file_hashes(self, files: Sequence[str]) -> List[Tuple[str, str]]:
        """(root-relative path, sha256) per file, sorted by rel path —
        the per-file half of the scan key.  The non-``.py`` artifacts the
        finish-phase checkers read (committed root ``*.json`` benches,
        ``docs/*.md`` generated tables) are folded in too: a hand-edited
        STATE_MACHINES.md or a corrupted BENCH_*.json must be a cache
        MISS, or the drift-as-finding contract dies in the warm path."""
        seen = {}
        for path in list(files) + self._artifact_files():
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            if rel not in seen:
                seen[rel] = _sha256_file(path)
        return sorted(seen.items())

    def _artifact_files(self) -> List[str]:
        out = []
        # committed bench artifacts (bench-schema reads them), generated
        # doc tables (event-registry/state-machine drift checks), and the
        # delegated validator sources under scripts/ (bench-schema
        # imports check_bench_schema.py even when `scripts` is not among
        # the scanned paths) — same stance as analysis_sources_hash:
        # editing any input re-runs everything
        for dirname, suffix in ((".", ".json"), ("docs", ".md"),
                                ("scripts", ".py")):
            d = os.path.join(self.root, dirname)
            try:
                for fn in sorted(os.listdir(d)):
                    if fn.endswith(suffix):
                        out.append(os.path.join(d, fn))
            except OSError:
                pass
        # the event registry is loaded from run.root by its checker even
        # when the scan paths don't cover it (partial invocations)
        reg = os.path.join(self.root, "deepspeed_tpu", "telemetry",
                           "event_registry.py")
        if os.path.isfile(reg):
            out.append(reg)
        return out

    def scan_key(self, checker_names: Sequence[str],
                 hashes: Sequence[Tuple[str, str]]) -> str:
        doc = {"version": VERSION,
               "checkers": sorted(checker_names),
               "files": list(hashes),
               "analysis": analysis_sources_hash()}
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True).encode()).hexdigest()

    # -------------------------------------------------------------- replay

    def _load(self) -> Optional[dict]:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(doc, dict) or doc.get("version") != VERSION:
            return None
        return doc

    def lookup(self, key: str,
               hashes: Sequence[Tuple[str, str]]) -> Optional[dict]:
        """The stored run for ``key``, or None.  Belt-and-braces: every
        per-file record's hash must still match the hash the key was
        computed from (a corrupted store reads as a miss).  The scanned
        set may be a subset of the hashed set — checkers with narrow
        ``applies()`` scopes skip files that still feed the key."""
        doc = self._load()
        if doc is None:
            return None
        rec = doc.get("runs", {}).get(key)
        if rec is None:
            return None
        want = dict(hashes)
        for rel, entry in rec.get("per_file", {}).items():
            if entry.get("hash") != want.get(rel):
                return None
        self._touch(doc, key)
        return rec

    def _touch(self, doc: dict, key: str) -> None:
        """Refresh ``key``'s recency on a warm HIT — the eviction order
        is LRU by *use*, and the everyday invocation that always hits
        must never be the one evicted by eight one-off runs."""
        order = [k for k in doc.get("order", []) if k != key] + [key]
        if order == doc.get("order"):
            return
        doc["order"] = order
        try:
            _atomic_write_text(self.path, json.dumps(doc, sort_keys=True))
        except OSError:
            pass

    def findings_of(self, rec: dict) -> List[Finding]:
        out = []
        for rel in sorted(rec.get("per_file", {})):
            for line, checker, message in rec["per_file"][rel]["findings"]:
                out.append(Finding(rel, line, checker, message))
        for path, line, checker, message in rec.get("cross", []):
            out.append(Finding(path, line, checker, message))
        out.sort(key=lambda f: f.sort_key)
        return out

    # --------------------------------------------------------------- store

    def result_of(self, rec: dict) -> "CachedResult":
        return CachedResult(rec, self.findings_of(rec))

    def store(self, key: str, checker_names: Sequence[str],
              hashes: Sequence[Tuple[str, str]], scanned: Sequence[str],
              findings: Sequence[Finding], suppressed: int) -> None:
        doc = self._load() or {"version": VERSION, "order": [], "runs": {}}
        scanned_set = set(scanned)
        per_file: Dict[str, dict] = {
            rel: {"hash": h, "findings": []}
            for rel, h in hashes if rel in scanned_set}
        cross = []
        for f in findings:
            if f.path in per_file:
                per_file[f.path]["findings"].append(
                    [f.line, f.checker, f.message])
            else:
                cross.append([f.path, f.line, f.checker, f.message])
        doc["runs"][key] = {
            "checkers": sorted(checker_names),
            "files_scanned": len(scanned),
            "suppressed": suppressed,
            "per_file": per_file,
            "cross": cross,
        }
        order = [k for k in doc.get("order", []) if k != key] + [key]
        for stale in order[:-MAX_RUNS]:
            doc["runs"].pop(stale, None)
        doc["order"] = order[-MAX_RUNS:]
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            _atomic_write_text(self.path, json.dumps(doc, sort_keys=True))
        except OSError:
            pass  # a read-only tree still lints, just never warm


class CachedResult:
    """Replayed run with the Runner's exact output surface — ``to_json``
    and ``summary`` go through the same ``core.render_*`` helpers the
    live Runner uses, so warm output is byte-identical to cold by
    construction (asserted in tier-1)."""

    from_cache = True

    def __init__(self, rec: dict, findings: List[Finding]):
        self.findings = findings
        self.checker_names = list(rec["checkers"])
        self.files_scanned = int(rec["files_scanned"])
        self.suppressed_count = int(rec["suppressed"])

    def to_json(self) -> str:
        return render_json(self.checker_names, self.files_scanned,
                           self.suppressed_count, self.findings)

    def summary(self) -> str:
        return render_summary(self.files_scanned, self.suppressed_count,
                              self.findings)
