"""Load a universal (atom-layout) checkpoint into a live engine.

Analog of the reference's ``load_universal_checkpoint`` path
(ref: runtime/engine.py:958, checkpoint/universal_checkpoint.py
load_hp_checkpoint_state) which maps per-parameter atom files onto each
rank's local flat fragments via ``utils/tensor_fragment.py``.  Here the
mapping is: atom (global fp32 ndarray) → `jax.device_put` under the engine's
current sharding — any mesh/stage/dtype target works, which is the entire
point of the universal format.
"""

from typing import Optional

import jax
import numpy as np

from ..utils.logging import log_dist
from .ds_to_universal import EXP_AVG, EXP_AVG_SQ, FP32_WEIGHT, _MOMENT_NAMES, load_universal_atoms


def _rebuild_tree(template, flat, prefix=(), cast_like=True):
    if isinstance(template, dict):
        return {k: _rebuild_tree(v, flat, prefix + (str(k), ), cast_like) for k, v in template.items()}
    name = ".".join(prefix)
    val = flat[name]
    if cast_like:
        val = np.asarray(val, template.dtype)
    return val


def _replace_moment_trees(opt_state, param_template, atoms, step=None):
    """Return opt_state with per-param moment subtrees replaced from atoms
    and scalar step/count fields set to the checkpoint's step (so e.g. Adam
    bias correction resumes at the right t, not at 1)."""
    pset = set(param_template)

    def moment_flat(atom_name):
        return {p: atoms[p][atom_name] for p in atoms if atom_name in atoms[p]}

    def visit(node, name_hint):
        if hasattr(node, "_fields"):
            return type(node)(*[visit(getattr(node, f), f) for f in node._fields])
        if isinstance(node, tuple):
            return tuple(visit(x, name_hint) for x in node)
        if isinstance(node, list):
            return [visit(x, name_hint) for x in node]
        if isinstance(node, dict):
            from .ds_to_universal import _flatten_with_names
            flat = _flatten_with_names(node)
            if set(flat) == pset and name_hint in _MOMENT_NAMES:
                wanted = _MOMENT_NAMES[name_hint]
                source = moment_flat(wanted)
                if source and set(source) != pset:
                    missing = sorted(pset - set(source))[:5]
                    raise ValueError(
                        f"universal checkpoint '{wanted}' atoms do not cover the engine's "
                        f"parameters (missing e.g. {missing}); refusing a partial optimizer "
                        f"restore — pass load_optimizer_states=False to load weights only")
                if source:
                    return _rebuild_tree(node, source)
            return {k: visit(v, k) for k, v in node.items()}
        if step is not None and name_hint in ("step", "count") and np.ndim(node) == 0:
            return np.asarray(step, getattr(node, "dtype", np.int32))
        return node

    return visit(opt_state, "")


def load_universal_checkpoint(engine, universal_dir: str, tag: Optional[str] = None,
                              load_optimizer_states: bool = True):
    import os
    universal_dir = os.path.abspath(universal_dir)
    if os.path.isdir(os.path.join(universal_dir, "zero")):
        path = universal_dir
    else:
        if tag is None:
            with open(os.path.join(universal_dir, "latest_universal")) as f:
                tag = f.read().strip()
        path = os.path.join(universal_dir, str(tag))

    atoms = load_universal_atoms(path)
    assert engine.state is not None, "materialize engine state first (run a batch or pass params)"
    from .ds_to_universal import _flatten_with_names, canonicalize_param_name
    # atoms carry topology-invariant names (legacy dirs may predate the
    # canonicalization — normalize them too); remap onto THIS engine's param
    # namespace, which may be a pipeline-stage tree
    atoms = {canonicalize_param_name(k): v for k, v in atoms.items()}
    host_params = jax.tree.map(lambda x: np.asarray(x), engine.state.params)
    target_names = _flatten_with_names(host_params)
    missing = [t for t in target_names if canonicalize_param_name(t) not in atoms]
    if missing:
        raise ValueError(f"universal checkpoint does not cover the engine's parameters "
                         f"(missing e.g. {sorted(missing)[:5]})")
    atoms = {t: atoms[canonicalize_param_name(t)] for t in target_names}
    import json
    step = None
    meta_path = os.path.join(path, "universal_meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            step = json.load(f).get("step")

    fp32_flat = {p: a[FP32_WEIGHT] for p, a in atoms.items()}
    use_master = engine.state.master != ()

    # params in compute dtype (host_params already copied for the name check)
    new_params = _rebuild_tree(host_params, fp32_flat)
    placed_params = jax.device_put(new_params, engine.state_shardings.params)

    new_master = ()
    if use_master:
        host_master = jax.tree.map(lambda x: np.asarray(x), engine.state.master)
        new_master = jax.device_put(_rebuild_tree(host_master, fp32_flat), engine.state_shardings.master)

    new_opt = engine.state.opt_state
    if load_optimizer_states:
        host_opt = jax.tree.map(lambda x: np.asarray(x), engine.state.opt_state)
        template = fp32_flat  # key set
        new_opt = _replace_moment_trees(host_opt, template, atoms, step=step)
        new_opt = jax.device_put(new_opt, engine.state_shardings.opt_state)

    engine.state = engine.state._replace(params=placed_params, master=new_master, opt_state=new_opt)
    if step is not None:
        engine.state = engine.state._replace(
            step=jax.device_put(np.asarray(step, np.int32), engine.state_shardings.step))
    log_dist(f"loaded universal checkpoint from {path} ({len(atoms)} params)", ranks=[0])
    return engine
