"""Sharded checkpoint save/load over orbax.

TPU-native analog of the reference checkpoint path
(ref: runtime/engine.py:3274 save_checkpoint / :2928 load_checkpoint and the
pluggable ``runtime/checkpoint_engine/``).  Key differences by design:

* The reference writes per-rank shard files
  (``zero_pp_rank_X_mp_rank_XX_optim_states.pt``) whose layout bakes in the
  (TP, PP, DP) topology, requiring the offline Universal Checkpoint converter
  (ref: deepspeed/checkpoint/ds_to_universal.py) to reshape.  Orbax stores
  the GLOBAL logical array with sharding metadata, so restoring onto a
  different mesh/topology is native — UCP semantics for free.
* Saves are async-capable (orbax AsyncCheckpointer) which covers the Nebula
  tiered/async engine's role (ref: deepspeed/nebula/).

Layout: ``<save_dir>/<tag>/state`` (orbax tree) + ``<save_dir>/<tag>/meta.json``
+ ``<save_dir>/latest`` tag file (same contract as the reference's `latest`).
"""

import json
import os
from typing import Optional

import jax
import orbax.checkpoint as ocp

from ..utils.logging import log_dist, logger


def _tag_path(save_dir, tag):
    return os.path.join(os.path.abspath(save_dir), str(tag))


def save_checkpoint(engine, save_dir, tag=None, client_state=None, save_latest=True):
    assert engine.state is not None, "engine has no state to checkpoint yet"
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    path = _tag_path(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    state_dict = {
        "params": engine.state.params,
        "master": engine.state.master if engine.state.master != () else None,
        "opt_state": engine.state.opt_state,
        "step": engine.state.step,
        "scaler": engine.state.scaler._asdict(),
        "skipped_steps": engine.state.skipped_steps,
    }
    # pluggable engine (ref: runtime/checkpoint_engine/ + nebula async):
    # "nebula": {"enabled": true} or checkpoint.checkpoint_engine "async" →
    # the save streams in the background (singleton checkpointer); training
    # continues immediately and the write is fenced at the next save/load
    from ..runtime.checkpoint_engine import make_checkpoint_engine
    pd = engine._config._param_dict
    kind = "async" if pd.get("nebula", {}).get("enabled", False) else \
        pd.get("checkpoint", {}).get("checkpoint_engine", "orbax")
    ck = make_checkpoint_engine(kind)
    ck.save(state_dict, os.path.join(path, "state"))

    meta = {
        "tag": str(tag),
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "zero_stage": engine.zero_stage,
        "lr_scheduler": engine.lr_scheduler.state_dict() if hasattr(engine.lr_scheduler, "state_dict") else None,
        "client_state": client_state or {},
    }
    if jax.process_index() == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f, indent=2, default=str)
        if save_latest:
            with open(os.path.join(os.path.abspath(save_dir), "latest"), "w") as f:
                f.write(str(tag))
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return True


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True, load_module_only=False):
    from ..runtime.checkpoint_engine import wait_for_pending_saves
    wait_for_pending_saves()  # fence any in-flight async (nebula-style) save
    load_dir = os.path.abspath(load_dir)
    if tag is None:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file at {load_dir}; nothing restored")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    path = _tag_path(load_dir, tag)
    if engine.state is None:
        raise RuntimeError("Engine state must be materialized before load_checkpoint "
                           "(run one batch or pass params to initialize)")

    # Build the abstract target from the CURRENT state + shardings: orbax
    # reshards on restore, giving universal-checkpoint semantics across mesh
    # changes (ref: deepspeed/checkpoint/ds_to_universal.py made obsolete).
    target = {
        "params": _abstract_like(engine.state.params, engine.state_shardings.params),
        "master": _abstract_like(engine.state.master, engine.state_shardings.master)
                  if engine.state.master != () else None,
        "opt_state": _abstract_like(engine.state.opt_state, engine.state_shardings.opt_state),
        "step": _abstract_like(engine.state.step, engine.state_shardings.step),
        "scaler": _abstract_like(engine.state.scaler._asdict(), engine.state_shardings.scaler._asdict()),
        "skipped_steps": _abstract_like(engine.state.skipped_steps, engine.state_shardings.skipped_steps),
    }
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(os.path.join(path, "state"), target)

    from ..runtime.engine import TrainState
    from ..runtime.fp16.loss_scaler import LossScalerState
    scaler = LossScalerState(**restored["scaler"])
    new_state = TrainState(
        step=restored["step"],
        params=restored["params"],
        master=restored["master"] if restored["master"] is not None else (),
        opt_state=restored["opt_state"] if load_optimizer_states and not load_module_only
                  else engine.state.opt_state,
        scaler=scaler,
        skipped_steps=restored["skipped_steps"],
    )
    engine.state = new_state

    client_state = {}
    meta_path = os.path.join(path, "meta.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        client_state = meta.get("client_state", {})
        if meta.get("lr_scheduler") and hasattr(engine.lr_scheduler, "load_state_dict"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    log_dist(f"loaded checkpoint {path}", ranks=[0])
    return path, client_state


def _abstract_like(tree, shardings):
    return jax.tree.map(lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s), tree, shardings)
