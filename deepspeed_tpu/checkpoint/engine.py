"""Sharded checkpoint save/load over orbax — crash-safe.

TPU-native analog of the reference checkpoint path
(ref: runtime/engine.py:3274 save_checkpoint / :2928 load_checkpoint and the
pluggable ``runtime/checkpoint_engine/``).  Key differences by design:

* The reference writes per-rank shard files
  (``zero_pp_rank_X_mp_rank_XX_optim_states.pt``) whose layout bakes in the
  (TP, PP, DP) topology, requiring the offline Universal Checkpoint converter
  (ref: deepspeed/checkpoint/ds_to_universal.py) to reshape.  Orbax stores
  the GLOBAL logical array with sharding metadata, so restoring onto a
  different mesh/topology is native — UCP semantics for free.
* Saves are async-capable (orbax AsyncCheckpointer) which covers the Nebula
  tiered/async engine's role (ref: deepspeed/nebula/).

Layout: ``<save_dir>/<tag>/state`` (orbax tree) + ``<save_dir>/<tag>/meta.json``
+ ``<save_dir>/<tag>/manifest.json`` (crc32 of every file in the tag) +
``<save_dir>/latest`` tag file (same contract as the reference's `latest`).

Durability contract (docs/RESILIENCE.md) — the save sequence is ordered so
a crash at ANY point leaves a loadable directory:

  1. state tree            → ``<tag>/state``       (orbax; maybe async)
  2. meta.json             → atomic write           [site ckpt.meta_write]
  3. extra state (host-tier ``host_opt_group*.npz``) into the tag dir
  4. FENCE: the async (nebula-style) background write is committed durable
  5. manifest.json         → atomic write           [site ckpt.manifest_write]
  6. latest                → atomic publish         [site ckpt.latest_publish]
  7. retention: keep-last-K older tags pruned

``latest`` is published strictly post-fence: a crash before (6) leaves the
previous checkpoint published and the new tag either complete-but-unlinked
or detectably torn.  ``load_checkpoint`` validates the tag the ``latest``
file points at (exists + meta parses + manifest verifies) and falls back
to the newest VALID tag with a warning — never an opaque orbax error.
"""

import json
import os
import shutil
from typing import Callable, List, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from ..resilience import atomic_io, events
from ..resilience import fault_injection as fi
from ..resilience.retry import RetryPolicy, retry_call
from ..utils.logging import log_dist, logger

# checkpoint metadata writes are tiny and latency-insensitive: retry hard
_CKPT_RETRY = RetryPolicy(max_attempts=4, base_delay_s=0.02, max_delay_s=0.5,
                          budget_s=5.0)


def _tag_path(save_dir, tag):
    return os.path.join(os.path.abspath(save_dir), str(tag))


# ------------------------------------------------------------ tag validity

def read_meta(tag_dir: str) -> Optional[dict]:
    meta_path = os.path.join(tag_dir, "meta.json")
    try:
        with open(meta_path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def checkpoint_tag_valid(save_dir: str, tag: str,
                         crc_scope: str = "all") -> Tuple[bool, str]:
    """Is ``<save_dir>/<tag>`` a loadable checkpoint?  Requires the tag
    directory and orbax state tree to exist, ``meta.json`` to parse, and —
    when a manifest was written — checksums to verify per ``crc_scope``:

    * ``"all"``  — every file incl. the orbax state tree (the load-path
      default: detecting silent state rot costs one extra read).
    * ``"meta"`` — manifest files OUTSIDE ``state/`` only (meta.json,
      host_opt npz): the ``verify_checksums_on_load=False`` opt-out for
      very large checkpoints.
    * ``"none"`` — structure only: used by retention, which must not
      re-read every byte of every retained checkpoint on each save."""
    path = _tag_path(save_dir, tag)
    if not os.path.isdir(path):
        return False, "tag directory missing"
    if not os.path.isdir(os.path.join(path, "state")):
        return False, "state tree missing"
    if read_meta(path) is None:
        return False, "meta.json missing or unparseable"
    if crc_scope != "none":
        match = None if crc_scope == "all" else \
            (lambda rel: not rel.replace(os.sep, "/").startswith("state/"))
        errors = atomic_io.verify_manifest(path, match=match)
        if errors:
            return False, f"manifest verification failed: {errors[0]}" + \
                (f" (+{len(errors) - 1} more)" if len(errors) > 1 else "")
    return True, "ok"


def list_tags(save_dir: str) -> List[str]:
    """Candidate tag directories, newest first (by recorded global_steps,
    falling back to directory mtime)."""
    save_dir = os.path.abspath(save_dir)
    if not os.path.isdir(save_dir):
        return []

    def order(tag):
        path = _tag_path(save_dir, tag)
        meta = read_meta(path)
        steps = meta.get("global_steps", -1) if meta else -1
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            mtime = 0.0
        return (steps, mtime)

    # sorted(): os.listdir order is filesystem-dependent, and the (steps,
    # mtime) sort below is stable — ties would otherwise resolve in disk
    # order, making newest-valid-tag fallback differ across machines
    tags = [d for d in sorted(os.listdir(save_dir))
            if os.path.isdir(os.path.join(save_dir, d, "state"))
            or os.path.exists(os.path.join(save_dir, d, "meta.json"))]
    return sorted(tags, key=order, reverse=True)


def find_newest_valid_tag(save_dir: str, exclude=(),
                          crc_scope: str = "all") -> Optional[str]:
    for tag in list_tags(save_dir):
        if tag in exclude:
            continue
        ok, _why = checkpoint_tag_valid(save_dir, tag, crc_scope=crc_scope)
        if ok:
            return tag
    return None


def _read_latest_tag(save_dir: str) -> Optional[str]:
    try:
        with open(os.path.join(save_dir, "latest")) as f:
            return f.read().strip()
    except OSError:
        return None


def _apply_retention(save_dir: str, keep_last_n: Optional[int], current_tag: str):
    """Keep-last-K: prune the oldest tag directories beyond ``keep_last_n``.
    The just-written tag AND the tag ``latest`` currently points at are
    always kept (they can differ under ``save_latest=False`` — deleting the
    published target would leave the pointer dangling).  Only VALID tags
    count toward the budget — a torn tag is deleted outright rather than
    occupying a retention slot while being unloadable."""
    if not keep_last_n or keep_last_n <= 0:
        return
    protected = {str(current_tag), _read_latest_tag(save_dir)}
    tags = list_tags(save_dir)
    kept = 0
    for tag in tags:  # newest first
        if tag in protected:
            kept += 1
            continue
        # structure-only validity: a crc sweep here would re-read every
        # byte of every retained checkpoint on each save
        ok, why = checkpoint_tag_valid(save_dir, tag, crc_scope="none")
        if ok and kept < keep_last_n:
            kept += 1
            continue
        path = _tag_path(save_dir, tag)
        try:
            shutil.rmtree(path)
        except OSError as e:
            logger.warning(f"checkpoint retention: could not delete {path}: {e}")
            continue
        events.emit("resilience/ckpt_retention_delete")
        log_dist(f"checkpoint retention (keep_last_n={keep_last_n}): deleted "
                 f"{'invalid ' if not ok else ''}tag {path}", ranks=[0])


# ------------------------------------------------------------------- save

def save_checkpoint(engine, save_dir, tag=None, client_state=None, save_latest=True,
                    extra_state_cb: Optional[Callable[[str], None]] = None):
    """Crash-safe save (ordering in the module docstring).  ``extra_state_cb``
    runs with the tag directory AFTER the state save is issued and BEFORE
    the manifest/latest publication — the engine uses it to persist the
    host-tier optimizer npz files inside the same durability fence."""
    assert engine.state is not None, "engine has no state to checkpoint yet"
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    save_dir = os.path.abspath(save_dir)
    path = _tag_path(save_dir, tag)
    os.makedirs(path, exist_ok=True)

    state_dict = {
        "params": engine.state.params,
        "master": engine.state.master if engine.state.master != () else None,
        "opt_state": engine.state.opt_state,
        "step": engine.state.step,
        "scaler": engine.state.scaler._asdict(),
        "skipped_steps": engine.state.skipped_steps,
    }
    # pluggable engine (ref: runtime/checkpoint_engine/ + nebula async):
    # "nebula": {"enabled": true} or checkpoint.checkpoint_engine "async" →
    # the save streams in the background (singleton checkpointer) and is
    # fenced durable below, before `latest` is published
    from ..runtime.checkpoint_engine import make_checkpoint_engine
    pd = engine._config._param_dict
    # the VALIDATED config (pydantic-coerced types), not the raw dict — a
    # json "keep_last_n": "3" must not crash retention at save time
    ckpt_cfg = getattr(engine._config, "checkpoint_config", None)
    kind = "async" if pd.get("nebula", {}).get("enabled", False) else \
        (getattr(ckpt_cfg, "checkpoint_engine", None) or "orbax")
    ck = make_checkpoint_engine(kind)
    # [site ckpt.state_save] is polled inside the engine's retried save
    ck.save(state_dict, os.path.join(path, "state"))

    meta = {
        "tag": str(tag),
        "global_steps": engine.global_steps,
        "global_samples": engine.global_samples,
        "zero_stage": engine.zero_stage,
        "lr_scheduler": engine.lr_scheduler.state_dict() if hasattr(engine.lr_scheduler, "state_dict") else None,
        "client_state": client_state or {},
    }
    if jax.process_index() == 0:
        retry_call(
            lambda: atomic_io.atomic_write_json(
                os.path.join(path, "meta.json"), meta, site="ckpt.meta_write",
                indent=2, default=str),
            _CKPT_RETRY, site="ckpt.meta_write")
    if extra_state_cb is not None:
        extra_state_cb(path)
    # FENCE: an async (nebula-style) background write must be durable
    # before the checkpoint is checksummed and published — this is the
    # ordering fix for the crash window where `latest` named a checkpoint
    # whose array data was still streaming
    ck.commit(tag)
    if jax.process_index() == 0:
        retry_call(lambda: atomic_io.write_manifest(path), _CKPT_RETRY,
                   site="ckpt.manifest_write")
        if save_latest:
            retry_call(
                lambda: atomic_io.atomic_write_text(
                    os.path.join(save_dir, "latest"), str(tag),
                    site="ckpt.latest_publish"),
                _CKPT_RETRY, site="ckpt.latest_publish")
            events.emit("resilience/ckpt_published")
        _apply_retention(save_dir, getattr(ckpt_cfg, "keep_last_n", None), str(tag))
    log_dist(f"saved checkpoint {path}", ranks=[0])
    return True


# ------------------------------------------------------------------- load

def _resolve_tag(load_dir: str, tag, from_latest: bool, crc_scope: str = "all"):
    """Validate the requested tag; when it came from ``latest`` and is
    invalid (torn save, corrupt file, deleted directory), fall back to the
    newest valid tag instead of surfacing an opaque orbax error."""
    ok, why = checkpoint_tag_valid(load_dir, tag, crc_scope=crc_scope)
    if ok:
        return tag
    events.emit("resilience/ckpt_invalid_tag")
    if not from_latest:
        # an EXPLICITLY requested tag is never silently substituted
        raise FileNotFoundError(
            f"checkpoint tag '{tag}' at {load_dir} is not loadable ({why})")
    # the fallback scan honors the same crc scope the primary tag got —
    # an opt-out deployment must not pay (or be failed by) state/-tree
    # checksums it asked to skip
    fallback = find_newest_valid_tag(load_dir, exclude={str(tag)}, crc_scope=crc_scope)
    if fallback is None:
        raise FileNotFoundError(
            f"'latest' points at tag '{tag}' which is not loadable ({why}), "
            f"and no valid fallback tag exists under {load_dir}")
    logger.warning(f"'latest' points at tag '{tag}' which is not loadable "
                   f"({why}); falling back to newest valid tag '{fallback}'")
    events.emit("resilience/ckpt_fallback")
    return fallback


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True, load_module_only=False):
    from ..runtime.checkpoint_engine import wait_for_pending_saves
    wait_for_pending_saves()  # fence any in-flight async (nebula-style) save
    load_dir = os.path.abspath(load_dir)
    from_latest = tag is None
    if from_latest:
        latest = os.path.join(load_dir, "latest")
        if not os.path.exists(latest):
            logger.warning(f"no 'latest' file at {load_dir}; nothing restored")
            return None, {}
        with open(latest) as f:
            tag = f.read().strip()
    cc = getattr(engine._config, "checkpoint_config", None)
    crc_scope = "all" if getattr(cc, "verify_checksums_on_load", True) else "meta"
    tag = _resolve_tag(load_dir, tag, from_latest, crc_scope=crc_scope)
    path = _tag_path(load_dir, tag)
    if engine.state is None:
        raise RuntimeError("Engine state must be materialized before load_checkpoint "
                           "(run one batch or pass params to initialize)")

    # Build the abstract target from the CURRENT state + shardings: orbax
    # reshards on restore, giving universal-checkpoint semantics across mesh
    # changes (ref: deepspeed/checkpoint/ds_to_universal.py made obsolete).
    target = {
        "params": _abstract_like(engine.state.params, engine.state_shardings.params),
        "master": _abstract_like(engine.state.master, engine.state_shardings.master)
                  if engine.state.master != () else None,
        "opt_state": _abstract_like(engine.state.opt_state, engine.state_shardings.opt_state),
        "step": _abstract_like(engine.state.step, engine.state_shardings.step),
        "scaler": _abstract_like(engine.state.scaler._asdict(), engine.state_shardings.scaler._asdict()),
        "skipped_steps": _abstract_like(engine.state.skipped_steps, engine.state_shardings.skipped_steps),
    }
    def _restore():
        fi.check("ckpt.state_restore")
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(os.path.join(path, "state"), target)

    restored = retry_call(_restore, _CKPT_RETRY, site="ckpt.state_restore")

    from ..runtime.engine import TrainState
    from ..runtime.fp16.loss_scaler import LossScalerState
    scaler = LossScalerState(**restored["scaler"])
    new_state = TrainState(
        step=restored["step"],
        params=restored["params"],
        master=restored["master"] if restored["master"] is not None else (),
        opt_state=restored["opt_state"] if load_optimizer_states and not load_module_only
                  else engine.state.opt_state,
        scaler=scaler,
        skipped_steps=restored["skipped_steps"],
    )
    engine.state = new_state

    client_state = {}
    meta = read_meta(path)
    if meta is not None:
        engine.global_steps = meta.get("global_steps", 0)
        engine.global_samples = meta.get("global_samples", 0)
        client_state = meta.get("client_state", {})
        if meta.get("lr_scheduler") and hasattr(engine.lr_scheduler, "load_state_dict"):
            engine.lr_scheduler.load_state_dict(meta["lr_scheduler"])
    log_dist(f"loaded checkpoint {path}", ranks=[0])
    return path, client_state


def _abstract_like(tree, shardings):
    return jax.tree.map(lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s), tree, shardings)
