"""Universal Checkpoint (UCP) converter.

TPU-native analog of ``deepspeed/checkpoint/ds_to_universal.py``
(ref: ds_to_universal.py:112 extract_zero_shards, :232 merge_tp_slices).

The reference has to run a multi-pass offline job because its shard files
(`zero_pp_rank_X_mp_rank_XX_optim_states.pt`) bake the (TP, PP, DP) topology
into flattened 1-D partitions: extracting a parameter means slicing every
rank's flat buffer and re-gluing TP slices with pattern-specific cat axes.
Orbax checkpoints store each parameter as a GLOBAL logical array, so the
"universal" form here is simply one directory per parameter holding its fp32
weight + optimizer moments as host numpy files — the same "atom" layout the
reference produces (`<param>/fp32.pt`, `<param>/exp_avg.pt`, ...), written as
``.npy``.

Why keep the converter at all (instead of "orbax does it"): the atom layout
is the reference's *interchange format* — it decouples a checkpoint from
mesh/stage/dtype/optimizer-partitioning so that a differently-configured run
(or another framework) can consume it, and it is browsable/editable with
nothing but numpy.

CLI:  python -m deepspeed_tpu.checkpoint.ds_to_universal \
          --input_folder ckpts --output_folder ckpts_universal [--tag ...]
"""

import argparse
import json
import os
import shutil
from typing import Dict, Optional

import numpy as np

from ..utils.logging import logger

# atom file names (same vocabulary as the reference's universal checkpoint)
FP32_WEIGHT = "fp32"
EXP_AVG = "exp_avg"
EXP_AVG_SQ = "exp_avg_sq"
STEP = "step"

_MOMENT_NAMES = {
    # optax-style state field → atom name
    "mu": EXP_AVG,
    "nu": EXP_AVG_SQ,
    "m": EXP_AVG,
    "v": EXP_AVG_SQ,
    "exp_avg": EXP_AVG,
    "exp_avg_sq": EXP_AVG_SQ,
    "momentum": EXP_AVG,
    "accumulator": EXP_AVG_SQ,  # adagrad
    "trace": EXP_AVG,
}


def canonicalize_param_name(name: str) -> str:
    """Topology-invariant atom name.

    Pipeline-stage trees name the same weights differently (``body.block.*``
    for the stacked transformer blocks, ``layer_0.embed_tokens`` /
    ``layer_N.{norm,lm_head}`` for the ends — see runtime/pipe/module.py);
    the universal layout stores everything under the plain model's names so
    a checkpoint saved at one (TP, PP, DP) topology loads at any other
    (ref: the reference's name normalization across parallel layouts in
    checkpoint/ds_to_universal.py merge_tp_slices + reshape_meg_2d.py)."""
    parts = name.split(".")
    if len(parts) > 2 and parts[0] == "body" and parts[1] == "block":
        return ".".join(["model", "layers"] + parts[2:])
    if len(parts) > 1 and parts[0].startswith("layer_") and parts[0][len("layer_"):].isdigit():
        return ".".join(parts[1:])
    return name


def _flatten_with_names(tree, prefix=()) -> Dict[str, np.ndarray]:
    """Flax param dict → {'layers.0.attention.q.kernel': ndarray}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten_with_names(v, prefix + (str(k), )))
    elif tree is None or (isinstance(tree, (tuple, list)) and len(tree) == 0):
        pass
    else:
        out[".".join(prefix)] = np.asarray(tree)
    return out


def _find_moment_trees(opt_state, param_template: Dict[str, np.ndarray]):
    """Walk the optimizer state; any dict subtree whose flattened key-set
    matches the param tree is a per-param moment tree.  NamedTuple fields
    provide the moment names (mu/nu → exp_avg/exp_avg_sq)."""
    found = {}  # atom_name -> {param_name: ndarray}
    pset = set(param_template)

    def visit(node, name_hint):
        if hasattr(node, "_fields"):
            for f in node._fields:
                visit(getattr(node, f), f)
            return
        if isinstance(node, (tuple, list)):
            for x in node:
                visit(x, name_hint)
            return
        if isinstance(node, dict):
            flat = _flatten_with_names(node)
            if set(flat) == pset and name_hint in _MOMENT_NAMES:
                found.setdefault(_MOMENT_NAMES[name_hint], flat)
                return
            for k, v in node.items():
                visit(v, k)
            return

    visit(opt_state, "")
    return found


def convert_to_universal(input_folder: str,
                         output_folder: str,
                         tag: Optional[str] = None) -> str:
    """Read a deepspeed_tpu checkpoint and write the universal atom layout:

        <output_folder>/<tag>/zero/<param_name>/{fp32,exp_avg,exp_avg_sq}.npy
        <output_folder>/<tag>/universal_meta.json
        <output_folder>/latest_universal
    """
    import orbax.checkpoint as ocp

    input_folder = os.path.abspath(input_folder)
    if tag is None:
        with open(os.path.join(input_folder, "latest")) as f:
            tag = f.read().strip()
    src = os.path.join(input_folder, str(tag))

    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(src, "state"))

    # master (fp32) weights if present, else params upcast
    master = state.get("master")
    weights = _flatten_with_names(master if master is not None else state["params"])
    weights = {k: v.astype(np.float32) for k, v in weights.items()}
    moments = _find_moment_trees(state.get("opt_state"), weights)

    # atoms live under topology-invariant names
    canon = {k: canonicalize_param_name(k) for k in weights}
    if len(set(canon.values())) != len(canon):
        dupes = sorted({v for v in canon.values() if list(canon.values()).count(v) > 1})
        raise ValueError(f"canonical atom name collision: {dupes[:5]}")
    weights = {canon[k]: v for k, v in weights.items()}
    moments = {atom: {canon[k]: v for k, v in tree.items()} for atom, tree in moments.items()}

    dst = os.path.join(os.path.abspath(output_folder), str(tag))
    zero_dir = os.path.join(dst, "zero")
    if os.path.exists(zero_dir):
        shutil.rmtree(zero_dir)
    os.makedirs(zero_dir, exist_ok=True)

    for pname, w in weights.items():
        pdir = os.path.join(zero_dir, pname)
        os.makedirs(pdir, exist_ok=True)
        np.save(os.path.join(pdir, f"{FP32_WEIGHT}.npy"), w)
        for atom, tree in moments.items():
            np.save(os.path.join(pdir, f"{atom}.npy"), np.asarray(tree[pname], np.float32))

    meta = {
        "tag": str(tag),
        "step": int(np.asarray(state.get("step", 0))),
        "param_names": sorted(weights),
        "atoms": [FP32_WEIGHT] + sorted(moments),
        "source": src,
    }
    src_meta = os.path.join(src, "meta.json")
    if os.path.exists(src_meta):
        with open(src_meta) as f:
            meta["source_meta"] = json.load(f)
    from ..resilience.atomic_io import atomic_write_json, atomic_write_text
    atomic_write_json(os.path.join(dst, "universal_meta.json"), meta, indent=2)
    # same publication discipline as `latest`: the pointer lands atomically
    # after the converted checkpoint it names is fully on disk
    atomic_write_text(os.path.join(os.path.abspath(output_folder), "latest_universal"), str(tag))
    logger.info(f"universal checkpoint written: {dst} ({len(weights)} params, atoms={meta['atoms']})")
    return dst


def load_universal_atoms(universal_dir: str) -> Dict[str, Dict[str, np.ndarray]]:
    """{'param_name': {'fp32': ndarray, 'exp_avg': ..., 'exp_avg_sq': ...}}"""
    zero_dir = os.path.join(universal_dir, "zero")
    out = {}
    for root, _dirs, files in os.walk(zero_dir):
        npys = [f for f in files if f.endswith(".npy")]
        if not npys:
            continue
        pname = os.path.relpath(root, zero_dir).replace(os.sep, ".")
        out[pname] = {os.path.splitext(f)[0]: np.load(os.path.join(root, f)) for f in npys}
    return out


def main(args=None):
    p = argparse.ArgumentParser(description="Convert deepspeed_tpu checkpoint to universal atom layout")
    p.add_argument("--input_folder", required=True)
    p.add_argument("--output_folder", required=True)
    p.add_argument("--tag", default=None)
    a = p.parse_args(args)
    convert_to_universal(a.input_folder, a.output_folder, tag=a.tag)


if __name__ == "__main__":
    main()
