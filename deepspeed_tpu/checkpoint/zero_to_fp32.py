"""Consolidate a (possibly sharded) checkpoint into a single fp32 state dict.

TPU-native analog of ``deepspeed/utils/zero_to_fp32.py`` (ref:
get_fp32_state_dict_from_zero_checkpoint / convert_zero_checkpoint_to_fp32_state_dict).
The reference stitches per-rank flat ZeRO partitions back into full tensors;
orbax already stores global arrays, so consolidation is a host-side restore +
fp32 upcast of the master (or param) tree.

Also usable as a CLI:
    python -m deepspeed_tpu.checkpoint.zero_to_fp32 <ckpt_dir> <out_file.npz> [--tag t]
"""

import argparse
import os
from typing import Dict, Optional

import numpy as np

from ..utils.logging import logger
from .ds_to_universal import _flatten_with_names


def get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir: str,
                                             tag: Optional[str] = None) -> Dict[str, np.ndarray]:
    """Return {'dotted.param.name': fp32 ndarray} from the saved master
    (fp32) weights, falling back to the compute-dtype params upcast."""
    import orbax.checkpoint as ocp

    checkpoint_dir = os.path.abspath(checkpoint_dir)
    if tag is None:
        latest = os.path.join(checkpoint_dir, "latest")
        with open(latest) as f:
            tag = f.read().strip()
    path = os.path.join(checkpoint_dir, str(tag), "state")
    with ocp.StandardCheckpointer() as ckptr:
        state = ckptr.restore(path)
    src = state.get("master") or state["params"]
    flat = _flatten_with_names(src)
    return {k: np.asarray(v, np.float32) for k, v in flat.items()}


def convert_zero_checkpoint_to_fp32_state_dict(checkpoint_dir: str,
                                               output_file: str,
                                               tag: Optional[str] = None) -> str:
    """Write the consolidated fp32 state dict to ``output_file``:
    ``.npz`` (numpy archive) or ``.pt`` (torch.save, loadable by torch users
    migrating from the reference)."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=tag)
    if output_file.endswith(".pt") or output_file.endswith(".bin"):
        import torch
        torch.save({k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()}, output_file)
    else:
        if not output_file.endswith(".npz"):
            output_file += ".npz"
        from ..resilience.atomic_io import atomic_savez
        atomic_savez(output_file, dict(sd))
    logger.info(f"consolidated fp32 state dict: {output_file} ({len(sd)} tensors)")
    return output_file


def load_state_dict_from_zero_checkpoint(engine, checkpoint_dir: str, tag: Optional[str] = None):
    """Load the consolidated fp32 weights into a live engine (ref:
    zero_to_fp32.load_state_dict_from_zero_checkpoint)."""
    import jax

    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag=tag)

    def rebuild(tree, prefix=()):
        if isinstance(tree, dict):
            return {k: rebuild(v, prefix + (str(k), )) for k, v in tree.items()}
        name = ".".join(prefix)
        return sd[name]

    assert engine.state is not None, "materialize engine state first"
    new_params = rebuild(engine.state.params)
    cast = jax.tree.map(lambda x, p: np.asarray(x, p.dtype), new_params, engine.state.params)
    placed = jax.device_put(cast, engine.state_shardings.params)
    use_master = engine.state.master != ()
    new_master = jax.device_put(new_params, engine.state_shardings.master) if use_master else ()
    engine.state = engine.state._replace(params=placed, master=new_master)
    return engine


def main(args=None):
    p = argparse.ArgumentParser()
    p.add_argument("checkpoint_dir")
    p.add_argument("output_file")
    p.add_argument("--tag", default=None)
    a = p.parse_args(args)
    convert_zero_checkpoint_to_fp32_state_dict(a.checkpoint_dir, a.output_file, tag=a.tag)


if __name__ == "__main__":
    main()
