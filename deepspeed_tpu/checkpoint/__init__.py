"""Checkpointing: sharded save/load (orbax), universal atom-layout
interchange (ref: deepspeed/checkpoint/), fp32 consolidation (ref:
deepspeed/utils/zero_to_fp32.py)."""

from .engine import load_checkpoint, save_checkpoint
from .ds_to_universal import convert_to_universal, load_universal_atoms
from .universal import load_universal_checkpoint
from .zero_to_fp32 import (convert_zero_checkpoint_to_fp32_state_dict, get_fp32_state_dict_from_zero_checkpoint,
                           load_state_dict_from_zero_checkpoint)
