"""Legacy fused transformer layer — API parity.

ref: deepspeed/ops/transformer/transformer.py (DeepSpeedTransformerLayer /
DeepSpeedTransformerConfig backed by csrc/transformer/*.cu — the original
fused BERT-training kernels: fused QKV GEMM + softmax + dropout + layernorm).

On TPU the fusion IS the compiler's job: one jitted BertLayer produces the
same fused schedule XLA-side (gelu/bias/dropout folded into the GEMM
epilogues), so this module is a thin parity shim over models/bert.BertLayer
keeping the reference's constructor surface for code being migrated.
"""

from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

from ...models.bert import BertConfig, BertLayer


@dataclass
class DeepSpeedTransformerConfig:
    """ref: ops/transformer/transformer.py DeepSpeedTransformerConfig."""
    batch_size: int = -1
    hidden_size: int = 768
    intermediate_size: int = 3072
    heads: int = 12
    attn_dropout_ratio: float = 0.1
    hidden_dropout_ratio: float = 0.1
    num_hidden_layers: int = 12
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    local_rank: int = -1
    seed: int = -1
    fp16: bool = False
    pre_layer_norm: bool = True
    normalize_invertible: bool = False     # memory trick subsumed by remat
    gelu_checkpoint: bool = False          # ditto
    adjust_init_range: bool = True
    attn_dropout_checkpoint: bool = False
    stochastic_mode: bool = False
    return_tuple: bool = False
    training: bool = True

    def to_bert_config(self) -> BertConfig:
        # dropout ratios accepted for parity; BertLayer is deterministic
        # (dropout under jit is a model concern, not a kernel concern here).
        # initializer_range/adjust_init_range likewise accepted but unused:
        # BertLayer initializes at normal(0.02); load trained weights via
        # flax params when exact init parity matters
        return BertConfig(hidden_size=self.hidden_size,
                          intermediate_size=self.intermediate_size,
                          num_attention_heads=self.heads,
                          num_hidden_layers=self.num_hidden_layers,
                          layer_norm_eps=self.layer_norm_eps,
                          pre_layer_norm=self.pre_layer_norm,
                          dtype=jnp.float16 if self.fp16 else jnp.float32)


def DeepSpeedTransformerLayer(config: DeepSpeedTransformerConfig, initial_weights=None,
                              initial_biases=None):
    """ref: transformer.py DeepSpeedTransformerLayer(config) — returns the
    layer module; weights initialize on first apply (initial_weights/biases
    accepted for signature parity; load via flax params instead)."""
    return BertLayer(config.to_bert_config())
