"""Helpers for integrating sparse attention into models.

ref: deepspeed/ops/sparse_attention/sparse_attention_utils.py
(SparseAttentionUtils: pad_to_block_size, unpad_sequence_output,
extend_position_embedding, update_tokenizer_model_max_length,
replace_model_self_attention_with_sparse_self_attention).
"""

from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np


def pad_to_block_size(block: int, input_ids, attention_mask=None, token_type_ids=None,
                      position_ids=None, inputs_embeds=None, pad_token_id: int = 0):
    """Right-pad sequence tensors to a multiple of the block size
    (ref: sparse_attention_utils.py pad_to_block_size).  Returns
    (pad_len, padded tensors…) — mirror the reference's tuple contract."""
    ref = input_ids if input_ids is not None else inputs_embeds
    seq_len = ref.shape[1]
    pad_len = (-seq_len) % block

    def pad(x, value=0):
        if x is None or pad_len == 0:
            return x
        cfg = [(0, 0), (0, pad_len)] + [(0, 0)] * (x.ndim - 2)
        return jnp.pad(x, cfg, constant_values=value)

    return (pad_len, pad(input_ids, pad_token_id), pad(attention_mask), pad(token_type_ids),
            pad(position_ids), pad(inputs_embeds))


def unpad_sequence_output(pad_len: int, sequence_output):
    """ref: sparse_attention_utils.py unpad_sequence_output."""
    if pad_len == 0:
        return sequence_output
    return sequence_output[:, :-pad_len]


def extend_position_embedding(pos_embedding: jnp.ndarray, max_position: int):
    """Tile learned position embeddings to a longer context
    (ref: sparse_attention_utils.py extend_position_embedding)."""
    cur = pos_embedding.shape[0]
    if max_position <= cur:
        return pos_embedding[:max_position]
    reps = int(np.ceil(max_position / cur))
    return jnp.tile(pos_embedding, (reps, 1))[:max_position]
