"""Block-sparse attention layout configs.

ref: deepspeed/ops/sparse_attention/sparsity_config.py (SparsityConfig:10,
Dense:63, Fixed:95, Variable:239, BigBird:411, BSLongformer:546,
LocalSlidingWindow:674).  Layouts are [num_heads, num_blocks, num_blocks]
0/1 numpy arrays built host-side (they are static w.r.t. compilation); the
kernel (sparse_self_attention.py) turns them into block-gather index maps.

Construction is vectorized numpy rather than the reference's per-cell loops,
but each pattern reproduces the same semantics (local windows, global
rows/columns, random blocks, uni/bidirectional masking).
"""

import numpy as np


class SparsityConfig:
    """ref: sparsity_config.py:10."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head
        self.num_layout_heads = num_heads if different_layout_per_head else 1

    def setup_layout(self, seq_len):
        if seq_len % self.block != 0:
            raise ValueError(f"sequence length {seq_len} must be divisible by block {self.block}")
        num_blocks = seq_len // self.block
        return np.zeros((self.num_heads, num_blocks, num_blocks), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len):
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All-ones layout (ref: sparsity_config.py:63) — for testing parity."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False):
        super().__init__(num_heads, block, different_layout_per_head)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


def _sliding_window(layout, h, w_blocks, attention):
    nb = layout.shape[1]
    half = w_blocks // 2
    rows = np.arange(nb)[:, None]
    cols = np.arange(nb)[None, :]
    if attention == "bidirectional":
        win = (cols >= rows - half) & (cols <= rows + half)
    else:
        win = (cols >= rows - half) & (cols <= rows)
    layout[h] |= win.astype(layout.dtype)
    return layout


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformer style fixed local+global pattern
    (ref: sparsity_config.py:95)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False, num_local_blocks=4,
                 num_global_blocks=1, attention="bidirectional", horizontal_global_attention=False,
                 num_different_global_patterns=1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks != 0:
            raise ValueError(f"num_local_blocks {num_local_blocks} must be divisible by "
                             f"num_global_blocks {num_global_blocks}")
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("only uni/bidirectional attention supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires bidirectional attention")
        if num_different_global_patterns > 1 and not different_layout_per_head:
            raise ValueError("num_different_global_patterns>1 requires different_layout_per_head")
        if num_different_global_patterns > num_local_blocks // num_global_blocks:
            raise ValueError("num_different_global_patterns too large")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = num_different_global_patterns

    def _local(self, layout, h):
        nb = layout.shape[1]
        rows = np.arange(nb)[:, None]
        cols = np.arange(nb)[None, :]
        same_window = (rows // self.num_local_blocks) == (cols // self.num_local_blocks)
        if self.attention == "unidirectional":
            same_window &= cols <= rows
        layout[h] |= same_window.astype(layout.dtype)
        return layout

    def _global(self, layout, h):
        nb = layout.shape[1]
        first = self.num_local_blocks - (1 + h % self.num_different_global_patterns) * self.num_global_blocks
        end = nb - (nb % self.num_local_blocks)
        starts = list(range(first, end, self.num_local_blocks))
        if end < nb:  # short tail window
            starts.append(min(end + first, nb - self.num_global_blocks))
        for i in starts:
            sl = slice(i, i + self.num_global_blocks)
            # vertical global stripe; the final np.tril in make_layout
            # enforces causality for unidirectional attention
            layout[h, :, sl] = 1
            if self.horizontal_global_attention:
                layout[h, sl, :] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = self._local(layout, h)
            layout = self._global(layout, h)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local-window + indexed global blocks + random blocks
    (ref: sparsity_config.py:239)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False, num_random_blocks=0,
                 local_window_blocks=None, global_block_indices=None, global_block_end_indices=None,
                 attention="bidirectional", horizontal_global_attention=False, seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("only uni/bidirectional attention supported")
        if attention != "bidirectional" and horizontal_global_attention:
            raise ValueError("horizontal global attention requires bidirectional attention")
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global_block_end_indices must pair with global_block_indices")
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.rng = np.random.default_rng(seed)

    def _local(self, layout, h):
        nb = layout.shape[1]
        # consecutive windows of the listed sizes; last size repeats
        start = 0
        sizes = list(self.local_window_blocks)
        while start < nb:
            size = sizes.pop(0) if sizes else self.local_window_blocks[-1]
            end = min(start + size, nb)
            rows = np.arange(start, end)[:, None]
            cols = np.arange(start, end)[None, :]
            sub = np.ones((end - start, end - start), layout.dtype) if self.attention == "bidirectional" \
                else (cols <= rows).astype(layout.dtype)
            layout[h, start:end, start:end] |= sub
            start = end
        return layout

    def _global(self, layout, h):
        nb = layout.shape[1]
        pairs = []
        if self.global_block_end_indices is None:
            pairs = [(i, i + 1) for i in self.global_block_indices]
        else:
            pairs = list(zip(self.global_block_indices, self.global_block_end_indices))
        rows = np.arange(nb)[:, None]
        for s, e in pairs:
            if s >= nb:
                continue
            e = min(e, nb)
            if self.attention == "bidirectional":
                layout[h, :, s:e] = 1
            else:
                layout[h, :, s:e] = np.where(rows >= s, 1, layout[h, :, s:e])
            if self.horizontal_global_attention:
                layout[h, s:e, :] = 1
        return layout

    def _random(self, layout, h):
        nb = layout.shape[1]
        for row in range(nb):
            hi = nb if self.attention == "bidirectional" else row + 1
            k = min(self.num_random_blocks, hi)
            cols = self.rng.choice(hi, size=k, replace=False)
            layout[h, row, cols] = 1
        return layout

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            if self.num_random_blocks:
                layout = self._random(layout, h)
            layout = self._local(layout, h)
            layout = self._global(layout, h)
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """random + sliding window + global first blocks
    (ref: sparsity_config.py:411)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False, num_random_blocks=1,
                 num_sliding_window_blocks=3, num_global_blocks=1, attention="bidirectional", seed=0):
        super().__init__(num_heads, block, different_layout_per_head)
        if attention not in ("unidirectional", "bidirectional"):
            raise NotImplementedError("only uni/bidirectional attention supported")
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.rng = np.random.default_rng(seed)

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        for name, n in (("random", self.num_random_blocks), ("sliding", self.num_sliding_window_blocks),
                        ("global", self.num_global_blocks)):
            if nb < n:
                raise ValueError(f"num_{name}_blocks {n} exceeds number of block rows {nb}")
        for h in range(self.num_layout_heads):
            for row in range(nb):
                hi = nb if self.attention == "bidirectional" else row + 1
                cols = self.rng.choice(hi, size=min(self.num_random_blocks, hi), replace=False)
                layout[h, row, cols] = 1
            layout = _sliding_window(layout, h, self.num_sliding_window_blocks, self.attention)
            g = self.num_global_blocks
            layout[h, 0:g, :] = 1
            layout[h, :, 0:g] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """sliding window + indexed global blocks (ref: sparsity_config.py:546)."""

    def __init__(self, num_heads, block=16, different_layout_per_head=False, num_sliding_window_blocks=3,
                 global_block_indices=None, global_block_end_indices=None, attention="bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices if global_block_indices is not None else [0]
        self.global_block_end_indices = global_block_end_indices
        if global_block_end_indices is not None and len(global_block_end_indices) != len(self.global_block_indices):
            raise ValueError("global_block_end_indices must pair with global_block_indices")
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        nb = layout.shape[1]
        pairs = [(i, i + 1) for i in self.global_block_indices] if self.global_block_end_indices is None \
            else list(zip(self.global_block_indices, self.global_block_end_indices))
        for h in range(self.num_layout_heads):
            layout = _sliding_window(layout, h, self.num_sliding_window_blocks, self.attention)
            for s, e in pairs:
                if s >= nb:
                    continue
                e = min(e, nb)
                layout[h, :, s:e] = 1  # global columns
                layout[h, s:e, :] = 1  # global rows
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """purely-local sliding window (ref: sparsity_config.py:674)."""

    def __init__(self, num_heads, block=16, num_sliding_window_blocks=3, attention="unidirectional"):
        super().__init__(num_heads, block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len):
        layout = self.setup_layout(seq_len)
        for h in range(self.num_layout_heads):
            layout = _sliding_window(layout, h, self.num_sliding_window_blocks, self.attention)
        return self.check_and_propagate_first_head_layout(layout)


SPARSITY_CONFIG_REGISTRY = {
    "dense": DenseSparsityConfig,
    "fixed": FixedSparsityConfig,
    "variable": VariableSparsityConfig,
    "bigbird": BigBirdSparsityConfig,
    "bslongformer": BSLongformerSparsityConfig,
    "local_sliding_window": LocalSlidingWindowSparsityConfig,
}


def make_sparsity_config(mode_or_dict, num_heads=None, **kwargs):
    """Factory from the ds-config ``sparse_attention`` block
    (ref: runtime/config.py get_sparse_attention → mode dispatch)."""
    if isinstance(mode_or_dict, dict):
        d = dict(mode_or_dict)
        mode = d.pop("mode", "fixed")
        d.pop("enabled", None)
        num_heads = d.pop("num_heads", num_heads)
        return SPARSITY_CONFIG_REGISTRY[mode](num_heads=num_heads, **d)
    return SPARSITY_CONFIG_REGISTRY[mode_or_dict](num_heads=num_heads, **kwargs)
