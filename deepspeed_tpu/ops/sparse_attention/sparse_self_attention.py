"""Block-sparse self-attention kernel (gather-based, XLA/MXU-friendly).

ref: deepspeed/ops/sparse_attention/sparse_self_attention.py +
matmul.py/softmax.py (Triton block-sparse sdd/dsd matmuls).  The Triton
design materializes only nonzero blocks of QK^T.  The TPU-native analog:
for each (head, query-block-row) we GATHER the active key/value blocks
given by the static layout, run a dense [block × L·block] attention on the
gathered slab, and scatter nothing back (output is dense).  Compute and
memory scale with the number of active blocks L, not sequence length —
the same asymptotics as the Triton kernels, but expressed as static gathers
+ batched matmuls that XLA tiles onto the MXU.

All index maps are static numpy derived from the layout, so jit sees fixed
shapes; per-head layouts with different occupancy are padded to the max
row occupancy L_max (padded blocks are masked to -inf before softmax).
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .sparsity_config import SparsityConfig


def _row_gather_maps(layout: np.ndarray):
    """layout [H, nb, nb] → cols [H, nb, L] int32 (active col-block ids,
    padded with 0), valid [H, nb, L] bool."""
    H, nb, _ = layout.shape
    occ = layout.sum(-1).max()
    L = max(int(occ), 1)
    cols = np.zeros((H, nb, L), np.int32)
    valid = np.zeros((H, nb, L), bool)
    for h in range(H):
        for r in range(nb):
            c = np.nonzero(layout[h, r])[0]
            cols[h, r, :c.size] = c
            valid[h, r, :c.size] = True
    return cols, valid


def sparse_attention(q, k, v, layout: np.ndarray, block: int, causal: bool = False,
                     scale: Optional[float] = None, key_padding_mask=None):
    """q,k,v: [B, H, S, D] → [B, H, S, D] attending only where layout=1.

    ``layout``: static [H, nb, nb] 0/1 (nb = S/block).  ``causal`` applies
    token-level causality *within* the admitted blocks (the layout itself
    should already be lower-triangular for unidirectional configs).
    """
    B, H, S, D = q.shape
    nb = S // block
    assert layout.shape == (H, nb, nb), f"layout {layout.shape} != {(H, nb, nb)}"
    cols, valid = _row_gather_maps(layout)
    L = cols.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)

    cols_j = jnp.asarray(cols)            # [H, nb, L]
    valid_j = jnp.asarray(valid)

    qb = q.reshape(B, H, nb, block, D)
    kb = k.reshape(B, H, nb, block, D)
    vb = v.reshape(B, H, nb, block, D)

    # gather active key/value blocks per (h, row) WITHOUT any nb×nb temp:
    # per head, one XLA gather of [B, nb, L, block, D] — working set scales
    # with L (active blocks), which is the whole point of block sparsity
    def gather_blocks(x):
        # x: [B, H, nb, block, D] → [B, H, nb, L*block, D]
        def per_head(xh, colsh):
            # xh [B, nb, block, D], colsh [nb, L] → [B, nb, L, block, D]
            return jnp.take(xh, colsh, axis=1)

        g = jax.vmap(per_head, in_axes=(1, 0), out_axes=1)(x, cols_j)
        return g.reshape(B, H, nb, L * block, D)

    kg = gather_blocks(kb)
    vg = gather_blocks(vb)

    scores = jnp.einsum("bhrqd,bhrkd->bhrqk", qb, kg) * scale  # [B,H,nb,block,L*block]

    # mask: padded blocks, optional causal within gathered keys, padding mask
    neg = jnp.finfo(scores.dtype).min
    block_ok = jnp.repeat(valid_j, block, axis=-1)  # [H, nb, L*block]
    mask = block_ok[None, :, :, None, :]
    if causal:
        q_pos = (jnp.arange(nb)[:, None] * block + jnp.arange(block)[None, :])  # [nb, block]
        k_pos = (cols_j[..., :, None] * block + jnp.arange(block)[None, None, None, :])  # [H,nb,L,block]
        k_pos = k_pos.reshape(H, nb, L * block)
        mask = mask & (q_pos[None, None, :, :, None] >= k_pos[:, :, None, :][None])
    if key_padding_mask is not None:
        kp = jnp.asarray(key_padding_mask, bool).reshape(B, nb, block)  # True = keep
        # per head: gather the key-block mask rows for each query row
        kpg = jax.vmap(lambda colsh: jnp.take(kp, colsh, axis=1), out_axes=1)(cols_j)
        mask = mask & kpg.reshape(B, H, nb, L * block)[:, :, :, None, :]
    scores = jnp.where(mask, scores, neg)

    probs = jax.nn.softmax(scores, axis=-1)
    # rows with zero admitted keys (fully masked) produce nan-free zeros
    probs = jnp.where(mask.any(-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhrqk,bhrkd->bhrqd", probs, vg)
    return out.reshape(B, H, S, D)


class SparseSelfAttention:
    """Callable wrapper bound to a SparsityConfig (ref:
    sparse_self_attention.py:SparseSelfAttention — torch module; here a
    layout cache + functional apply)."""

    def __init__(self, sparsity_config: SparsityConfig, key_padding_mask_mode="add",
                 attn_mask_mode="mul", impl: str = "jnp"):
        # impl: "jnp" (differentiable golden, supports key_padding_mask) or
        # "pallas" (splash-style TPU kernel, fwd-only, no padding mask)
        assert impl in ("jnp", "pallas"), impl
        self.impl = impl
        self.sparsity_config = sparsity_config
        self._layouts = {}

    def get_layout(self, seq_len):
        if seq_len not in self._layouts:
            self._layouts[seq_len] = np.asarray(self.sparsity_config.make_layout(seq_len))
        return self._layouts[seq_len]

    def __call__(self, query, key, value, key_padding_mask=None, causal=None):
        S = query.shape[2]
        layout = self.get_layout(S)
        causal = (self.sparsity_config.attention == "unidirectional") \
            if causal is None and hasattr(self.sparsity_config, "attention") else bool(causal)
        import jax as _jax
        if self.impl == "pallas" and key_padding_mask is None \
                and _jax.devices()[0].platform == "tpu":
            # off-TPU the kernel would run the per-grid-step Python
            # interpreter — orders of magnitude slower than the jnp path
            from .pallas_kernel import sparse_attention_pallas
            return sparse_attention_pallas(query, key, value, layout,
                                           self.sparsity_config.block, causal=causal)
        return sparse_attention(query, key, value, layout, self.sparsity_config.block,
                                causal=causal, key_padding_mask=key_padding_mask)
