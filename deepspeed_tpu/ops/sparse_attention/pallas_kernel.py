"""Splash-style Pallas TPU block-sparse attention kernel.

ref: csrc/sparse_attention + deepspeed/ops/sparse_attention/{matmul,softmax}
(Triton block-sparse SDD/softmax/DSD kernels behind BigBird/Longformer
configs) — and jax's bundled splash-attention as the TPU design pattern:
the static layout's active-column table is passed as a SCALAR-PREFETCH
operand, and the KV BlockSpec ``index_map`` reads it, so the kernel's grid
only ever touches admitted blocks.  Dense work and DMA traffic scale with
the number of active blocks L, not nb² — the entire point of block
sparsity, now without the gather-based jnp path's [B, H, nb, L·block, D]
materialization.

The backward is the same design run twice (mirroring the FA2 split in
ops/flash_attention.py): a dq kernel sweeping each q row's admitted kv
blocks via the row-major table, and a dk/dv kernel sweeping each kv
column's admitted q blocks via the transposed table, both recomputing
p = exp(s - lse) per admitted tile from the lse the forward saved (O(S)
residuals).  No [S, S]-scale intermediate is ever materialized in either
direction, and grads touch only admitted blocks — the previous VJP re-ran
the jnp golden, gathering [B, H, nb, L·block, D] tensors.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(cols_ref, valid_ref, q_ref, k_ref, v_ref, o_ref, *rest, scale, causal, block, L,
            num_heads):
    lse_ref = rest[0] if len(rest) == 4 else None
    m_scr, l_scr, acc_scr = rest[-3:]
    bh = pl.program_id(0)
    r = pl.program_id(1)
    l = pl.program_id(2)
    h = bh % num_heads

    @pl.when(l == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        # bf16 operands straight into the MXU with f32 accumulation (casting
        # to f32 first runs the dots at ~1/8 MXU rate)
        q = q_ref[0]          # [block, d]
        k = k_ref[0]          # [block, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            col = cols_ref[h, r, l]
            qpos = r * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            kpos = col * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if causal:
            # rows whose every admitted key is causally masked: s == MASK
            # everywhere → p would be exp(0) = 1; zero them so the finalize
            # emits zeros like the jnp golden
            p = jnp.where(s > DEFAULT_MASK_VALUE * 0.5, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    # padded layout slots are skipped entirely (no DMA cost is saved for the
    # already-mapped block, but no FLOPs/accumulation happen)
    pl.when(valid_ref[h, r, l] != 0)(_compute)

    @pl.when(l == L - 1)
    def _finalize():
        # fully-masked rows (no admitted keys) emit zeros, matching the jnp
        # path's nan-free contract
        safe_l = jnp.maximum(l_scr[:], 1e-30)
        out = acc_scr[:] / safe_l
        o_ref[0] = jnp.where(l_scr[:] > 0, out, 0.0).astype(o_ref.dtype)
        if lse_ref is not None:
            # empty rows store +BIG so the backward's exp(s - lse) underflows
            # to exactly 0 for every (masked) score
            lse = jnp.where(l_scr[:] > 0, m_scr[:] + jnp.log(safe_l),
                            jnp.float32(3e38))
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref[0].shape)


def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta, *, scale, causal, block,
              r_idx, c_idx):
    """Shared backward tile math for one admitted (q-row, kv-col) block pair:
    returns (pr, ds) — both in the storage dtype, MXU-ready.  ``delta`` is
    the per-row rowsum(do·o) [block, 1]: a lane-broadcast HBM input would be
    [B·H, S, 128] f32 — 128× the O(S) data and 4× the DMA bytes of just
    re-reading the bf16 o block (narrower minor dims are not tile-legal),
    so callers compute it from the o/do blocks instead."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]
    s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = r_idx * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
        kpos = c_idx * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
        s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
    pr = jnp.exp(s - lse)                 # masked/empty entries underflow to 0
    dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = pr * (dp - delta) * scale
    return pr.astype(v.dtype), ds.astype(v.dtype)


def _dq_kernel(cols_ref, valid_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
               dq_scr, delta_scr, *, scale, causal, block, L, num_heads):
    bh = pl.program_id(0)
    r = pl.program_id(1)
    l = pl.program_id(2)
    h = bh % num_heads

    @pl.when(l == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)
        # the q row is fixed across the l sweep: compute its delta once
        delta_scr[:] = jnp.sum(do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                               axis=1, keepdims=True)

    def _compute():
        _, ds = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_scr[:], scale=scale,
                          causal=causal, block=block, r_idx=r, c_idx=cols_ref[h, r, l])
        dq_scr[:] += jax.lax.dot_general(ds, k_ref[0], (((1, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    pl.when(valid_ref[h, r, l] != 0)(_compute)

    @pl.when(l == L - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(rows_ref, valid_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref,
                dv_ref, dk_scr, dv_scr, *, scale, causal, block, L, num_heads):
    bh = pl.program_id(0)
    c = pl.program_id(1)
    l = pl.program_id(2)
    h = bh % num_heads

    @pl.when(l == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        # column-major sweep: the q row changes per tile, so delta is
        # per-tile here ([block, D] reduce — cheap next to the [block²] exp)
        delta = jnp.sum(do_ref[0].astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                        axis=1, keepdims=True)
        pr, ds = _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta, scale=scale,
                           causal=causal, block=block, r_idx=rows_ref[h, c, l], c_idx=c)
        dv_scr[:] += jax.lax.dot_general(pr, do_ref[0], (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dk_scr[:] += jax.lax.dot_general(ds, q_ref[0], (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    pl.when(valid_ref[h, c, l] != 0)(_compute)

    @pl.when(l == L - 1)
    def _finalize():
        # columns no row attends to emit zero grads
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _col_gather_maps(layout: np.ndarray):
    """layout [H, nb, nb] → rows [H, nb, Lt] int32 (active ROW-block ids per
    kv column, padded with 0), valid [H, nb, Lt] bool — the transposed twin
    of ``_row_gather_maps`` driving the dk/dv kernel's q sweep."""
    return _row_maps_of(layout.transpose(0, 2, 1))


def _row_maps_of(layout):
    from .sparse_self_attention import _row_gather_maps
    return _row_gather_maps(layout)


LANE = 128  # lse is stored lane-broadcast (TPU tiling: minor dim 128)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _pallas_vjp(layout_key, block, causal, scale, interpret, q, k, v):
    out, _ = _fwd_impl(q, k, v, _layout_of(layout_key), block, causal, scale, interpret,
                       emit_lse=False)
    return out


def _layout_of(layout_key):
    H = len(layout_key)
    layout = np.asarray(layout_key, np.int64).reshape(H, -1)
    nb = int(np.sqrt(layout.shape[1]))
    return layout.reshape(H, nb, nb)


def _pallas_vjp_fwd(layout_key, block, causal, scale, interpret, q, k, v):
    out, lse = _fwd_impl(q, k, v, _layout_of(layout_key), block, causal, scale, interpret,
                         emit_lse=True)
    return out, (q, k, v, out, lse)


def _pallas_vjp_bwd(layout_key, block, causal, scale, interpret, res, g):
    # dq/dkv Pallas kernels driven by the same scalar-prefetch layout maps
    # as the forward (row-major sweep for dq, column-major for dk/dv) —
    # the saved O(S) lse replaces any softmax recompute and no [S, S]-scale
    # intermediate is ever materialized (the old VJP re-ran the jnp golden,
    # gathering [B, H, nb, L·block, D] score tensors)
    q, k, v, out, lse = res
    return _bwd_impl(q, k, v, out, lse, g, _layout_of(layout_key), block, causal, scale,
                     interpret)


_pallas_vjp.defvjp(_pallas_vjp_fwd, _pallas_vjp_bwd)


def sparse_attention_pallas(q, k, v, layout, block: int, causal: bool = False,
                            scale: Optional[float] = None,
                            interpret: Optional[bool] = None):
    """Block-sparse attention over [B, H, S, D] with a static [H, nb, nb]
    layout — same contract as ``sparse_self_attention.sparse_attention``
    (key_padding_mask unsupported; use the jnp path for that).  Forward and
    backward both run splash-style kernels; training touches only admitted
    blocks end to end."""
    layout = np.asarray(layout, np.int64)
    layout_key = tuple(map(tuple, layout.reshape(layout.shape[0], -1).tolist()))
    return _pallas_vjp(layout_key, block, causal, scale, interpret, q, k, v)


def _prep(q, layout, block, scale, interpret):
    B, H, S, D = q.shape
    nb = S // block
    assert layout.shape == (H, nb, nb), f"layout {layout.shape} != {(H, nb, nb)}"
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return B, H, S, D, nb, scale, interpret


def _fwd_impl(q, k, v, layout: np.ndarray, block: int, causal: bool = False,
              scale: Optional[float] = None, interpret: Optional[bool] = None,
              emit_lse: bool = False):
    B, H, S, D, nb, scale, interpret = _prep(q, layout, block, scale, interpret)
    cols, valid = _row_maps_of(layout)
    L = cols.shape[-1]

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    cols_j = jnp.asarray(cols.reshape(H, nb, L), jnp.int32)
    valid_j = jnp.asarray(valid.reshape(H, nb, L), jnp.int32)

    kernel = functools.partial(_kernel, scale=scale, causal=causal, block=block, L=L,
                               num_heads=H)
    num_heads_static = H  # read by the index_map lambdas below
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, nb, L),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, r, l, cols, valid: (bh, r, 0)),
            # the kv block index comes from the layout's active-column table
            pl.BlockSpec((1, block, D),
                         lambda bh, r, l, cols, valid: (bh, cols[bh % num_heads_static, r, l], 0)),
            pl.BlockSpec((1, block, D),
                         lambda bh, r, l, cols, valid: (bh, cols[bh % num_heads_static, r, l], 0)),
        ],
        out_specs=[pl.BlockSpec((1, block, D), lambda bh, r, l, cols, valid: (bh, r, 0))] + ([
            pl.BlockSpec((1, block, LANE), lambda bh, r, l, cols, valid: (bh, r, 0))]
            if emit_lse else []),
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B * H, S, D), q.dtype)] + ([
            jax.ShapeDtypeStruct((B * H, S, LANE), jnp.float32)] if emit_lse else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(cols_j, valid_j, qf, kf, vf)
    if emit_lse:
        return out[0].reshape(B, H, S, D), out[1]
    return out[0].reshape(B, H, S, D), None


def _bwd_impl(q, k, v, out, lse, g, layout: np.ndarray, block: int, causal, scale, interpret):
    B, H, S, D, nb, scale, interpret = _prep(q, layout, block, scale, interpret)
    cols, valid = _row_maps_of(layout)
    rows_t, valid_t = _col_gather_maps(layout)
    L, Lt = cols.shape[-1], rows_t.shape[-1]

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    of = out.reshape(B * H, S, D)
    dof = g.reshape(B * H, S, D).astype(q.dtype)
    H_ = H  # read by index_map lambdas

    def qrow(bh, r, l, cols, valid):
        return (bh, r, 0)

    def kgather(bh, r, l, cols, valid):
        return (bh, cols[bh % H_, r, l], 0)

    # dq: row-major sweep, same maps as the forward
    cols_j = jnp.asarray(cols.reshape(H, nb, L), jnp.int32)
    valid_j = jnp.asarray(valid.reshape(H, nb, L), jnp.int32)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, block=block, L=L, num_heads=H),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb, L),
            in_specs=[
                pl.BlockSpec((1, block, D), qrow),
                pl.BlockSpec((1, block, D), kgather),
                pl.BlockSpec((1, block, D), kgather),
                pl.BlockSpec((1, block, D), qrow),
                pl.BlockSpec((1, block, D), qrow),
                pl.BlockSpec((1, block, LANE), qrow),
            ],
            out_specs=pl.BlockSpec((1, block, D), qrow),
            scratch_shapes=[pltpu.VMEM((block, D), jnp.float32),
                            pltpu.VMEM((block, 1), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(cols_j, valid_j, qf, kf, vf, of, dof, lse)

    # dk/dv: column-major sweep over the transposed maps; q/o/do/lse blocks
    # are gathered by the active-ROW table while k/v/outputs sit at column c
    rows_j = jnp.asarray(rows_t.reshape(H, nb, Lt), jnp.int32)
    validt_j = jnp.asarray(valid_t.reshape(H, nb, Lt), jnp.int32)

    def qgather(bh, c, l, rows, valid):
        return (bh, rows[bh % H_, c, l], 0)

    def kcol(bh, c, l, rows, valid):
        return (bh, c, 0)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, block=block, L=Lt,
                          num_heads=H),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb, Lt),
            in_specs=[
                pl.BlockSpec((1, block, D), qgather),
                pl.BlockSpec((1, block, D), kcol),
                pl.BlockSpec((1, block, D), kcol),
                pl.BlockSpec((1, block, D), qgather),
                pl.BlockSpec((1, block, D), qgather),
                pl.BlockSpec((1, block, LANE), qgather),
            ],
            out_specs=[
                pl.BlockSpec((1, block, D), kcol),
                pl.BlockSpec((1, block, D), kcol),
            ],
            scratch_shapes=[
                pltpu.VMEM((block, D), jnp.float32),
                pltpu.VMEM((block, D), jnp.float32),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((B * H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, S, D), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(rows_j, validt_j, qf, kf, vf, of, dof, lse)
    return (dq.reshape(B, H, S, D), dk.reshape(B, H, S, D), dv.reshape(B, H, S, D))
