"""Splash-style Pallas TPU block-sparse attention kernel.

ref: csrc/sparse_attention + deepspeed/ops/sparse_attention/{matmul,softmax}
(Triton block-sparse SDD/softmax/DSD kernels behind BigBird/Longformer
configs) — and jax's bundled splash-attention as the TPU design pattern:
the static layout's active-column table is passed as a SCALAR-PREFETCH
operand, and the KV BlockSpec ``index_map`` reads it, so the kernel's grid
only ever touches admitted blocks.  Dense work and DMA traffic scale with
the number of active blocks L, not nb² — the entire point of block
sparsity, now without the gather-based jnp path's [B, H, nb, L·block, D]
materialization.

The kernel is wrapped in a ``jax.custom_vjp`` whose backward recomputes
through the differentiable jnp path (``sparse_attention``) — training works,
the forward-pass memory/DMA win is the kernel's contribution.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(cols_ref, valid_ref, q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
            scale, causal, block, L, num_heads):
    bh = pl.program_id(0)
    r = pl.program_id(1)
    l = pl.program_id(2)
    h = bh % num_heads

    @pl.when(l == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [block, d]
        k = k_ref[0].astype(jnp.float32)          # [block, d]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            col = cols_ref[h, r, l]
            qpos = r * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
            kpos = col * block + jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        if causal:
            # rows whose every admitted key is causally masked: s == MASK
            # everywhere → p would be exp(0) = 1; zero them so the finalize
            # emits zeros like the jnp golden
            p = jnp.where(s > DEFAULT_MASK_VALUE * 0.5, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    # padded layout slots are skipped entirely (no DMA cost is saved for the
    # already-mapped block, but no FLOPs/accumulation happen)
    pl.when(valid_ref[h, r, l] != 0)(_compute)

    @pl.when(l == L - 1)
    def _finalize():
        # fully-masked rows (no admitted keys) emit zeros, matching the jnp
        # path's nan-free contract
        safe_l = jnp.maximum(l_scr[:], 1e-30)
        out = acc_scr[:] / safe_l
        o_ref[0] = jnp.where(l_scr[:] > 0, out, 0.0).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _pallas_vjp(layout_key, block, causal, scale, interpret, q, k, v):
    H = len(layout_key)
    layout = np.asarray(layout_key, np.int64).reshape(H, -1)
    nb = int(np.sqrt(layout.shape[1]))
    return _fwd_impl(q, k, v, layout.reshape(H, nb, nb), block, causal, scale, interpret)


def _pallas_vjp_fwd(layout_key, block, causal, scale, interpret, q, k, v):
    return _pallas_vjp(layout_key, block, causal, scale, interpret, q, k, v), (q, k, v)


def _pallas_vjp_bwd(layout_key, block, causal, scale, interpret, res, g):
    # backward recomputes through the differentiable jnp golden
    from .sparse_self_attention import sparse_attention
    H = len(layout_key)
    layout = np.asarray(layout_key, np.int64).reshape(H, -1)
    nb = int(np.sqrt(layout.shape[1]))
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: sparse_attention(q_, k_, v_, layout.reshape(H, nb, nb), block,
                                            causal=causal, scale=scale), q, k, v)
    return vjp(g)


_pallas_vjp.defvjp(_pallas_vjp_fwd, _pallas_vjp_bwd)


def sparse_attention_pallas(q, k, v, layout, block: int, causal: bool = False,
                            scale: Optional[float] = None,
                            interpret: Optional[bool] = None):
    """Block-sparse attention over [B, H, S, D] with a static [H, nb, nb]
    layout — same contract as ``sparse_self_attention.sparse_attention``
    (key_padding_mask unsupported; use the jnp path for that).  Forward runs
    the splash kernel; backward recomputes through the jnp golden."""
    layout = np.asarray(layout, np.int64)
    layout_key = tuple(map(tuple, layout.reshape(layout.shape[0], -1).tolist()))
    return _pallas_vjp(layout_key, block, causal, scale, interpret, q, k, v)


def _fwd_impl(q, k, v, layout: np.ndarray, block: int, causal: bool = False,
              scale: Optional[float] = None,
              interpret: Optional[bool] = None):
    from .sparse_self_attention import _row_gather_maps

    B, H, S, D = q.shape
    nb = S // block
    assert layout.shape == (H, nb, nb), f"layout {layout.shape} != {(H, nb, nb)}"
    cols, valid = _row_gather_maps(layout)
    L = cols.shape[-1]
    scale = scale if scale is not None else 1.0 / np.sqrt(D)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    qf = q.reshape(B * H, S, D)
    kf = k.reshape(B * H, S, D)
    vf = v.reshape(B * H, S, D)
    cols_j = jnp.asarray(cols.reshape(H, nb, L), jnp.int32)
    valid_j = jnp.asarray(valid.reshape(H, nb, L), jnp.int32)

    kernel = functools.partial(_kernel, scale=scale, causal=causal, block=block, L=L,
                               num_heads=H)
    num_heads_static = H  # read by the index_map lambdas below
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B * H, nb, L),
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, r, l, cols, valid: (bh, r, 0)),
            # the kv block index comes from the layout's active-column table
            pl.BlockSpec((1, block, D),
                         lambda bh, r, l, cols, valid: (bh, cols[bh % num_heads_static, r, l], 0)),
            pl.BlockSpec((1, block, D),
                         lambda bh, r, l, cols, valid: (bh, cols[bh % num_heads_static, r, l], 0)),
        ],
        out_specs=pl.BlockSpec((1, block, D), lambda bh, r, l, cols, valid: (bh, r, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(cols_j, valid_j, qf, kf, vf)
    return out.reshape(B, H, S, D)
