"""Block-sparse attention (ref: deepspeed/ops/sparse_attention/)."""

from .sparse_attention_utils import extend_position_embedding, pad_to_block_size, unpad_sequence_output
from .sparse_self_attention import SparseSelfAttention, sparse_attention
from .sparsity_config import (BigBirdSparsityConfig, BSLongformerSparsityConfig, DenseSparsityConfig,
                              FixedSparsityConfig, LocalSlidingWindowSparsityConfig, SparsityConfig,
                              VariableSparsityConfig, make_sparsity_config)
