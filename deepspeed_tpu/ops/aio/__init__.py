"""Python handle API over the native async-IO engine.

Reference: ``deepspeed/ops/aio`` + ``csrc/aio/py_lib/deepspeed_py_aio_handle
.cpp`` — ``aio_handle`` with async_pread/async_pwrite/sync_pread/
sync_pwrite/wait.  Buffers are numpy arrays (the host staging side of a
device↔host↔NVMe pipeline; ``jax.device_get/put`` moves the device leg).
"""

import ctypes
from pathlib import Path
from typing import Optional

import numpy as np

from ..op_builder import AsyncIOBuilder

_LIB = None


def _lib():
    global _LIB
    if _LIB is None:
        lib = AsyncIOBuilder().load()
        lib.aio_handle_new.restype = ctypes.c_void_p
        lib.aio_handle_new.argtypes = [ctypes.c_longlong, ctypes.c_longlong, ctypes.c_int, ctypes.c_int]
        lib.aio_handle_free.argtypes = [ctypes.c_void_p]
        lib.aio_pread.restype = ctypes.c_int
        lib.aio_pread.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_longlong, ctypes.c_longlong]
        lib.aio_pwrite.restype = ctypes.c_int
        lib.aio_pwrite.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_longlong, ctypes.c_longlong]
        lib.aio_wait.restype = ctypes.c_longlong
        lib.aio_wait.argtypes = [ctypes.c_void_p]
        lib.aio_pending.restype = ctypes.c_longlong
        lib.aio_pending.argtypes = [ctypes.c_void_p]
        lib.aio_file_size.restype = ctypes.c_longlong
        lib.aio_file_size.argtypes = [ctypes.c_char_p]
        _LIB = lib
    return _LIB


class AsyncIOHandle:
    """ref: csrc/aio/py_lib aio_handle (block_size, queue_depth, thread_count,
    single_submit/overlap_events are implicit in the thread-pool design)."""

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 32,
                 thread_count: int = 4, use_o_direct: bool = False):
        self._lib = _lib()
        self._h = self._lib.aio_handle_new(block_size, queue_depth, thread_count,
                                           1 if use_o_direct else 0)
        self._refs = []  # keep submitted buffers alive until wait()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.aio_wait(h)
            self._lib.aio_handle_free(h)
            self._h = None

    @staticmethod
    def _check_buffer(buf: np.ndarray, writable: bool):
        assert isinstance(buf, np.ndarray) and buf.flags.c_contiguous, \
            "aio buffers must be C-contiguous numpy arrays"
        if writable:
            assert buf.flags.writeable

    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> None:
        self._check_buffer(buffer, writable=True)
        self._refs.append(buffer)
        rc = self._lib.aio_pread(self._h, buffer.ctypes.data_as(ctypes.c_void_p),
                                 str(path).encode(), offset, buffer.nbytes)
        assert rc == 0, f"aio_pread submit failed: {rc}"

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> None:
        self._check_buffer(buffer, writable=False)
        self._refs.append(buffer)
        rc = self._lib.aio_pwrite(self._h, buffer.ctypes.data_as(ctypes.c_void_p),
                                  str(path).encode(), offset, buffer.nbytes)
        assert rc == 0, f"aio_pwrite submit failed: {rc}"

    def wait(self) -> int:
        """Block until all submitted requests complete; returns the count.
        Raises on the first IO error (ref: aio_handle.wait semantics)."""
        n = self._lib.aio_wait(self._h)
        self._refs.clear()
        if n < 0:
            raise OSError(-int(n), f"async IO failed: errno {-int(n)}")
        return int(n)

    def pending(self) -> int:
        return int(self._lib.aio_pending(self._h))

    # sync conveniences (ref: deepspeed_py_aio.cpp sync_pread/sync_pwrite)
    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pread(buffer, path, offset)
        return self.wait()

    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pwrite(buffer, path, offset)
        return self.wait()


def file_size(path) -> int:
    n = _lib().aio_file_size(str(path).encode())
    if n < 0:
        raise OSError(-int(n), f"stat failed for {path}")
    return int(n)
