"""Fused LAMB (ref: csrc/lamb/fused_lamb_cuda_kernel.cu, deepspeed/ops/lamb).

Layer-wise adaptive rate: per-parameter trust ratio ||w|| / ||update||.
The CUDA kernel does a two-pass reduction per tensor; here each leaf's norms
fuse into the single XLA update program.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optimizer import GradientTransformation, resolve_lr, tree_zeros_like


class LambState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any
    exp_avg_sq: Any


def fused_lamb(lr=1e-3,
               betas=(0.9, 0.999),
               eps=1e-8,
               weight_decay=0.0,
               bias_correction=True,
               max_coeff=10.0,
               min_coeff=0.01) -> GradientTransformation:
    b1, b2 = betas

    def init(params):
        return LambState(step=jnp.zeros((), jnp.int32),
                         exp_avg=tree_zeros_like(params, jnp.float32),
                         exp_avg_sq=tree_zeros_like(params, jnp.float32))

    def update(grads, state: LambState, params=None):
        assert params is not None, "LAMB requires params for the trust ratio"
        step = state.step + 1
        lr_v = resolve_lr(lr, step)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state.exp_avg, g32)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state.exp_avg_sq, g32)
        if bias_correction:
            c1 = 1 - b1**step.astype(jnp.float32)
            c2 = 1 - b2**step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.ones((), jnp.float32)

        def leaf_update(m_, v_, p):
            p32 = p.astype(jnp.float32)
            u = (m_ / c1) / (jnp.sqrt(v_ / c2) + eps) + weight_decay * p32
            w_norm = jnp.linalg.norm(p32.reshape(-1))
            u_norm = jnp.linalg.norm(u.reshape(-1))
            trust = jnp.where(u_norm > 0, jnp.where(w_norm > 0, w_norm / u_norm, 1.0), 1.0)
            trust = jnp.clip(trust, min_coeff, max_coeff)
            return -lr_v * trust * u

        updates = jax.tree.map(leaf_update, m, v, params)
        return updates, LambState(step=step, exp_avg=m, exp_avg_sq=v)

    return GradientTransformation(init, update)


lamb = fused_lamb
