"""Adam / AdamW.

TPU-native replacement for the reference's FusedAdam
(ref: csrc/adam/multi_tensor_adam.cu + deepspeed/ops/adam/fused_adam.py:FusedAdam)
and CPUAdam (csrc/adam/cpu_adam_impl.cpp, AVX-vectorized — ref:
csrc/includes/cpu_adam.h:45).  One jitted pytree update == one fused kernel
sweep; ``adam_w_mode`` selects decoupled weight decay exactly as the CUDA
kernel's ``ADAM_MODE_1``.
"""

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from .optimizer import GradientTransformation, add_weight_decay, resolve_lr, tree_zeros_like


class AdamState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any  # m
    exp_avg_sq: Any  # v


def fused_adam(lr: float = 1e-3,
               betas=(0.9, 0.999),
               eps: float = 1e-8,
               weight_decay: float = 0.0,
               adam_w_mode: bool = True,
               bias_correction: bool = True,
               amsgrad: bool = False,
               wd_mask=None) -> GradientTransformation:
    if amsgrad:
        raise ValueError("FusedAdam does not support the AMSGrad variant (parity with ref fused_adam.py)")
    b1, b2 = betas

    def init(params):
        return AdamState(step=jnp.zeros((), jnp.int32),
                         exp_avg=tree_zeros_like(params, jnp.float32),
                         exp_avg_sq=tree_zeros_like(params, jnp.float32))

    def update(grads, state: AdamState, params=None):
        step = state.step + 1
        lr_v = resolve_lr(lr, step)
        grads32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        if not adam_w_mode:  # L2-regularisation mode: decay folded into grads
            grads32 = add_weight_decay(grads32, params, weight_decay, wd_mask)
        m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, state.exp_avg, grads32)
        v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * jnp.square(g), state.exp_avg_sq, grads32)
        if bias_correction:
            c1 = 1 - b1**step.astype(jnp.float32)
            c2 = 1 - b2**step.astype(jnp.float32)
        else:
            c1 = c2 = jnp.ones((), jnp.float32)
        updates = jax.tree.map(lambda m_, v_: -lr_v * (m_ / c1) / (jnp.sqrt(v_ / c2) + eps), m, v)
        if adam_w_mode and weight_decay > 0.0 and params is not None:
            if wd_mask is None:
                updates = jax.tree.map(lambda u, p: u - lr_v * weight_decay * p.astype(jnp.float32), updates, params)
            else:
                updates = jax.tree.map(
                    lambda u, p, msk: u - lr_v * weight_decay * p.astype(jnp.float32) if msk else u, updates, params,
                    wd_mask)
        return updates, AdamState(step=step, exp_avg=m, exp_avg_sq=v)

    return GradientTransformation(init, update)


def adam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, **kw):
    """torch.optim.Adam semantics (L2 mode)."""
    return fused_adam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adam_w_mode=False, **kw)


def adamw(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.01, **kw):
    """torch.optim.AdamW semantics (decoupled decay)."""
    return fused_adam(lr=lr, betas=betas, eps=eps, weight_decay=weight_decay, adam_w_mode=True, **kw)
