"""Pallas TPU paged (blocked-KV) decode attention.

TPU-native equivalent of the reference FastGen's blocked-flash/linear-KV
attention kernels (ref: deepspeed/inference/v2/kernels/ragged_ops —
``blocked_flash``, ``linear_blocked_kv_rotary``; KV geometry from
``inference/v2/ragged/kv_cache.py``).  The kernel attends a (small) chunk of
queries per sequence against that sequence's paged KV history, gathering
pages from the shared arena through the block table.

Implementation notes:
  * the block table and start positions ride in scalar-prefetch SMEM
    (``PrefetchScalarGridSpec``) so each grid step's page DMA address is
    computed from ``block_table[b, j]`` — the Pallas analog of the
    reference's atom-builder indirection (ragged/csrc/fast_host_buffer.cpp).
  * grid = (batch, pages); the page dimension is "arbitrary" (sequential)
    and carries the online-softmax state in VMEM scratch.  Each grid step
    DMAs one WHOLE page — [page, 2, n_kv, D], whose trailing block dims are
    the full array dims and therefore always tile-legal — and loops the kv
    heads in-kernel with per-head scratch.  (A per-head grid with a
    [page, 1, 1, D] block is rejected by the TPU tiling rules: the
    second-minor block dim 1 is neither 8-aligned nor the full n_kv dim.)
  * GQA: queries are laid out group-major ([B, n_kv, rep·C, D]) so each
    head iteration contracts its whole query group against the page.
  * pages whose first key is beyond the chunk's last visible position are
    skipped (`pl.when`), so decode cost scales with the sequence's true
    length, not max_pages — SplitFuse's "decode is O(context)" property.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _paged_kernel(bt_ref, sp_ref, q_ref, pg_ref, o_ref, *scr, page_size, max_pages, chunk,
                  scale, n_kv):
    b = pl.program_id(0)
    j = pl.program_id(1)
    ms, ls, accs = scr[:n_kv], scr[n_kv:2 * n_kv], scr[2 * n_kv:]

    @pl.when(j == 0)
    def _init():
        for hh in range(n_kv):
            ms[hh][:] = jnp.full_like(ms[hh], -jnp.inf)
            ls[hh][:] = jnp.zeros_like(ls[hh])
            accs[hh][:] = jnp.zeros_like(accs[hh])

    start = sp_ref[b]
    # last visible key position of this chunk is start + chunk - 1
    @pl.when(j * page_size <= start + chunk - 1)
    def _compute():
        for hh in range(n_kv):
            # bf16 operands straight into the MXU, f32 accumulation
            q = q_ref[0, hh]             # [repC, D]
            k = pg_ref[0, :, 0, hh]      # [page, D]
            v = pg_ref[0, :, 1, hh]      # [page, D]
            rep_c = q.shape[0]
            s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                    preferred_element_type=jnp.float32) * scale  # [repC, page]
            # row r of the group-major q block is chunk position r % chunk
            row_c = jax.lax.broadcasted_iota(jnp.int32, (rep_c, page_size), 0) % chunk
            kpos = j * page_size + jax.lax.broadcasted_iota(jnp.int32, (rep_c, page_size), 1)
            s = jnp.where(kpos <= start + row_c, s, DEFAULT_MASK_VALUE)
            m_prev = ms[hh][:]
            l_prev = ls[hh][:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            ls[hh][:] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            accs[hh][:] = accs[hh][:] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            ms[hh][:] = m_new

    @pl.when(j == max_pages - 1)
    def _finalize():
        for hh in range(n_kv):
            o_ref[0, hh] = (accs[hh][:] / jnp.maximum(ls[hh][:], 1e-30)).astype(o_ref.dtype)


def _paged_sharded(q, pages, block_table, start_pos, chunk_lens, page_size, interpret, mesh):
    """Run the paged kernel inside shard_map over the governing (trace) mesh.

    Mosaic custom calls cannot be auto-partitioned by GSPMD — the TP-sharded
    serving engine (inference/v2) traces this under a tensor-axis mesh, so the
    kernel wraps itself the way ``flash_attention._flash_sharded`` does.
    Attention is head-local: q shards on H, the page arena on its n_kv dim,
    block tables/positions replicate, and no collective is needed inside —
    the o_proj allreduce after it is GSPMD's to insert.  A tensor degree that
    does not divide n_kv replicates (correct, just not distributed)."""
    from jax.sharding import PartitionSpec as P

    from ..comm.mesh import TENSOR_AXIS
    h, n_kv = q.shape[2], pages.shape[3]
    tp = mesh.shape.get(TENSOR_AXIS, 1)
    head_axes = (TENSOR_AXIS, ) if tp > 1 and n_kv % tp == 0 and h % tp == 0 else ()
    qspec = P(None, None, head_axes or None, None)
    pspec = P(None, None, None, head_axes or None, None)
    if chunk_lens is None:
        fn = jax.shard_map(
            lambda q_, pg_, bt_, sp_: paged_attention_pallas(
                q_, pg_, bt_, sp_, None, page_size, interpret=interpret),
            mesh=mesh,
            in_specs=(qspec, pspec, P(None, None), P(None)),
            out_specs=qspec,
            check_vma=False)
        return fn(q, pages, block_table, start_pos)
    fn = jax.shard_map(
        lambda q_, pg_, bt_, sp_, cl_: paged_attention_pallas(
            q_, pg_, bt_, sp_, cl_, page_size, interpret=interpret),
        mesh=mesh,
        in_specs=(qspec, pspec, P(None, None), P(None), P(None)),
        out_specs=qspec,
        # pallas_call out_shapes carry no varying-mesh-axes annotation
        check_vma=False)
    return fn(q, pages, block_table, start_pos, chunk_lens)


def paged_attention_pallas(q, pages, block_table, start_pos, chunk_lens, page_size,
                           *, interpret: Optional[bool] = None):
    """Drop-in twin of ``models/llama_cache.paged_attention`` (jnp golden).

    q: [B, C, H, D]; pages: [P, page, 2, n_kv, D] (chunk K/V already
    written); block_table: [B, max_pages]; start_pos/chunk_lens: [B].
    """
    from ..comm.mesh import get_trace_mesh, in_manual_mesh
    if interpret is None:
        tm = get_trace_mesh()
        dev = tm.devices.flat[0] if tm is not None else jax.devices()[0]
        interpret = getattr(dev, "platform", "") != "tpu"
    if isinstance(q, jax.core.Tracer) and not in_manual_mesh():
        mesh = get_trace_mesh()
        if mesh is not None and mesh.size > 1:
            return _paged_sharded(q, pages, block_table, start_pos, chunk_lens, page_size,
                                  interpret, mesh)
    b, c, h, d = q.shape
    n_kv = pages.shape[3]
    max_pages = block_table.shape[1]
    rep = h // n_kv
    scale = 1.0 / (d**0.5)

    # group-major query layout: [B, n_kv, rep*C, D], row = r*C + c
    qg = q.transpose(0, 2, 1, 3).reshape(b, n_kv, rep, c, d).reshape(b, n_kv, rep * c, d)

    grid = (b, max_pages)
    kernel = functools.partial(_paged_kernel, page_size=page_size, max_pages=max_pages,
                               chunk=c, scale=scale, n_kv=n_kv)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                # q stays resident across the page sweep (index map constant in j)
                pl.BlockSpec((1, n_kv, rep * c, d), lambda b, j, bt, sp: (b, 0, 0, 0)),
                # one whole page: trailing dims (page, 2, n_kv, d) are the full
                # array dims → always tile-legal.  j is CLAMPED to the row's
                # last needed page: past it the index map repeats the same
                # page and Mosaic's pipeline skips the refetch — pages beyond
                # the true sequence length cost no DMA (they were still
                # copied pre-r4 even though pl.when skipped their compute)
                pl.BlockSpec((1, page_size, 2, n_kv, d),
                             lambda b, j, bt, sp:
                             (bt[b, jnp.minimum(j, (sp[b] + c - 1) // page_size)],
                              0, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, n_kv, rep * c, d), lambda b, j, bt, sp: (b, 0, 0, 0)),
            scratch_shapes=([pltpu.VMEM((rep * c, 1), jnp.float32)] * n_kv +
                            [pltpu.VMEM((rep * c, 1), jnp.float32)] * n_kv +
                            [pltpu.VMEM((rep * c, d), jnp.float32)] * n_kv),
        ),
        out_shape=jax.ShapeDtypeStruct((b, n_kv, rep * c, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(block_table, start_pos, qg, pages)

    out = out.reshape(b, n_kv, rep, c, d).reshape(b, h, c, d).transpose(0, 2, 1, 3)
    if chunk_lens is not None:
        valid = jnp.arange(c)[None, :] < chunk_lens[:, None]
        out = jnp.where(valid[..., None, None], out, 0)
    return out
