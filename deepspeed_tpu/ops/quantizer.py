"""Block quantization ops (int8/int4, symmetric/asymmetric).

Reference: ``csrc/quantization/{quantize.cu, dequantize.cu, quant_reduce.cu,
quantize_intX.cu}`` + ``deepspeed/ops/quantizer`` — block-quantized tensors
for ZeRO++ communication compression (qwZ weight all-gather, qgZ gradient
all-to-all) and weight-only inference quantization.

Pure-jnp implementations; XLA fuses the scale/cast chains, and the bit
packing (two int4 per int8 lane) lowers to the same shifts a hand kernel
would use.  Group-wise scales over the trailing dimension of each block.
"""

from typing import Tuple

import jax
import jax.numpy as jnp


def _blocked(x, block: int):
    n = x.size
    assert n % block == 0, f"size {n} not divisible by quant block {block}"
    return x.reshape(n // block, block)


def quantize_int8(x, block: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-block int8 (ref: quantize.cu symmetric path).
    Returns (q [n/block, block] int8, scales [n/block] f32)."""
    xb = _blocked(x.astype(jnp.float32), block)
    absmax = jnp.max(jnp.abs(xb), axis=1)
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q = jnp.clip(jnp.round(xb / scale[:, None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize_int8(q, scale, shape) -> jnp.ndarray:
    return (q.astype(jnp.float32) * scale[:, None]).reshape(shape)


def quantize_int4(x, block: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Symmetric per-block int4, two nibbles packed per uint8
    (ref: quantize_intX.cu).  Returns (packed [n/block, block/2] uint8,
    scales [n/block] f32)."""
    assert block % 2 == 0
    xb = _blocked(x.astype(jnp.float32), block)
    absmax = jnp.max(jnp.abs(xb), axis=1)
    scale = jnp.where(absmax == 0, 1.0, absmax / 7.0)
    q = jnp.clip(jnp.round(xb / scale[:, None]), -7, 7).astype(jnp.int8) + 8  # [1..15], 0 unused
    # halves layout: nibble i packs elements (i, i + block/2) — contiguous
    # slices keep the Pallas kernel (ops/quant_kernels.py) off gather paths
    # Mosaic cannot lower; pack and unpack agree, so the wire format is free
    lo = q[:, :block // 2].astype(jnp.uint8)
    hi = q[:, block // 2:].astype(jnp.uint8)
    return (lo | (hi << 4)), scale


def dequantize_int4(packed, scale, shape) -> jnp.ndarray:
    lo = (packed & 0xF).astype(jnp.int8) - 8
    hi = ((packed >> 4) & 0xF).astype(jnp.int8) - 8
    q = jnp.concatenate([lo, hi], axis=-1)  # halves layout (see quantize_int4)
    return (q.astype(jnp.float32) * scale[:, None]).reshape(shape)


def quantization_error(x, bits: int = 8, block: int = 256) -> jnp.ndarray:
    """Roundtrip residual (used by error-feedback compression)."""
    if bits == 8:
        q, s = quantize_int8(x, block)
        return x - dequantize_int8(q, s, x.shape).astype(x.dtype)
    q, s = quantize_int4(x, block)
    return x - dequantize_int4(q, s, x.shape).astype(x.dtype)


# ------------------------------------------------------------- sign (1-bit)

def pack_signs(x) -> jnp.ndarray:
    """1-bit sign compression: 8 signs per uint8 (ref: csrc/xpu/packbits and
    the compressed backend's bit packing).  Sizes not divisible by 8 are
    padded (``unpack_signs``'s n parameter drops the slack)."""
    flat = x.reshape(-1)
    pad = (-flat.size) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.ones((pad, ), flat.dtype)])
    bits = (flat >= 0).astype(jnp.uint8).reshape(-1, 8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(bits * weights[None, :], axis=1, dtype=jnp.uint8)


def unpack_signs(packed, n: int) -> jnp.ndarray:
    """Inverse of ``pack_signs``: ±1 float32 of length n."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (packed[:, None] >> shifts[None, :]) & 1
    return (bits.astype(jnp.float32) * 2.0 - 1.0).reshape(-1)[:n]
