"""Optimizer-transform core.

The reference implements its optimizer zoo as fused CUDA multi-tensor kernels
(ref: csrc/adam/multi_tensor_adam.cu, csrc/lamb/fused_lamb_cuda_kernel.cu,
csrc/lion, csrc/adagrad/cpu_adagrad.cpp) launched once over all params.  On
TPU "fused" is free: a single jitted update over the whole parameter pytree
compiles to one XLA program in which elementwise update math fuses into a
handful of kernels.  We use the optax ``GradientTransformation`` protocol
(init/update pairs) so DeepSpeed-named optimizers and raw optax transforms are
interchangeable — the engine only sees ``init_fn(params)`` and
``update_fn(grads, state, params)``.

Master-weight handling: these transforms keep fp32 optimizer state and expect
fp32 grads; the engine owns the bf16/fp16 ↔ fp32 boundary (mirroring
runtime/bf16_optimizer.py / runtime/fp16/fused_optimizer.py responsibilities).
"""

from typing import Any, Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp


class GradientTransformation(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Optional[Any]], Any]


def resolve_lr(lr, step):
    """lr may be a float or a schedule ``step -> lr`` (ref: the engine passes
    the JSON ``scheduler`` block down so the lr lives inside the compiled
    step instead of a host-side scheduler object)."""
    return lr(step) if callable(lr) else lr


def tree_cast(tree, dtype):
    return jax.tree.map(lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


def tree_zeros_like(tree, dtype=None):
    return jax.tree.map(lambda x: jnp.zeros_like(x, dtype=dtype or x.dtype), tree)


def global_norm(tree):
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def clip_by_global_norm(grads, max_norm):
    """ref: runtime/utils.py clip_grad_norm_ — but computed on the already
    fully-reduced gradient pytree, so no cross-rank norm reduction is needed
    (GSPMD has summed grads before this point)."""
    norm = global_norm(grads)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * scale, grads), norm


def add_weight_decay(updates, params, weight_decay, mask=None):
    if weight_decay == 0.0 or params is None:
        return updates
    if mask is None:
        return jax.tree.map(lambda u, p: u + weight_decay * p.astype(u.dtype), updates, params)
    return jax.tree.map(lambda u, p, m: u + (weight_decay * p.astype(u.dtype) if m else jnp.zeros_like(u)), updates,
                        params, mask)


def chain(*transforms: GradientTransformation) -> GradientTransformation:

    def init(params):
        return tuple(t.init(params) for t in transforms)

    def update(grads, state, params=None):
        new_state = []
        for t, s in zip(transforms, state):
            grads, s = t.update(grads, s, params)
            new_state.append(s)
        return grads, tuple(new_state)

    return GradientTransformation(init, update)


def scale(factor: float) -> GradientTransformation:
    return GradientTransformation(lambda p: (), lambda g, s, p=None: (jax.tree.map(lambda x: x * factor, g), s))


def apply_updates(params, updates):
    """params + updates, preserving param dtype (updates are the final deltas)."""
    return jax.tree.map(lambda p, u: (p.astype(jnp.float32) + u.astype(jnp.float32)).astype(p.dtype), params, updates)


def default_wd_mask(params):
    """Standard no-decay mask: skip 1-D params (biases, norms, scales)."""
    return jax.tree.map(lambda p: p.ndim > 1, params)
