"""Pallas TPU quantize/dequantize kernels (block int8 + packed int4).

ref: csrc/quantization/{quantize.cu, dequantize.cu, swizzled_quantize.cu,
quantize_intX.cu} — the reference's fused CUDA kernels behind ZeRO++ comm
compression (qwZ weight all-gather, qgZ gradient all-to-all).  The jnp
fallbacks in ops/quantizer.py compile to a reduce pass (absmax) plus an
elementwise pass — two full reads of the tensor; these kernels fuse the
per-block absmax, scale, round/clip, and (for int4) nibble packing into ONE
VMEM-resident pass per block, which is the whole advantage a hand kernel
has on a memory-bound op.

Layouts: x is viewed as [n_blocks, block]; scales are emitted lane-broadcast
[n_blocks, 128] (TPU block specs need (8/32, 128)-aligned tiles; int8/uint8
tiles need 32 sublanes, hence ROWS=32).  Wrappers return the same
(q, scales[n_blocks]) contract as ops/quantizer.py and fall back to the jnp
path off-TPU or for shapes the tiling can't cover.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from .quantizer import dequantize_int4, dequantize_int8, quantize_int4, quantize_int8

LANE = 128
ROWS = 256  # per-grid-cell rows: >= 32 (int8 sublane tile); larger amortizes grid overhead


def _q8_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)                       # [R, block]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)      # [R, 1]
    scale = jnp.where(absmax == 0, 1.0, absmax / 127.0)
    q_ref[...] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    s_ref[...] = jnp.broadcast_to(scale, s_ref.shape)


def _dq8_kernel(q_ref, s_ref, o_ref):
    scale = s_ref[...][:, :1]                                # [R, 1]
    o_ref[...] = (q_ref[...].astype(jnp.float32) * scale).astype(o_ref.dtype)


def _q4_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)                       # [R, block]
    absmax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / 7.0)
    q = jnp.clip(jnp.round(x / scale), -7, 7).astype(jnp.int32) + 8   # [1..15]
    half = q.shape[1] // 2
    lo = q[:, :half]   # halves layout (contiguous slices: Mosaic cannot
    hi = q[:, half:]   # lower the strided 0::2 interleave)
    q_ref[...] = (lo | (hi << 4)).astype(jnp.uint8)          # [R, block/2]
    s_ref[...] = jnp.broadcast_to(scale, s_ref.shape)


def _dq4_kernel(q_ref, s_ref, o_ref):
    packed = q_ref[...].astype(jnp.int32)                    # [R, block/2]
    scale = s_ref[...][:, :1]
    lo = (packed & 0xF) - 8
    hi = ((packed >> 4) & 0xF) - 8
    q = jnp.concatenate([lo, hi], axis=-1).astype(jnp.float32)  # halves layout
    o_ref[...] = (q * scale).astype(o_ref.dtype)


def _grid_ok(nb: int, block: int, half: bool = False) -> bool:
    inner = block // 2 if half else block
    return nb % ROWS == 0 and inner % LANE == 0


def _on_tpu() -> bool:
    return jax.devices()[0].platform == "tpu"


def quantize_int8_pallas(x, block: int = 256, interpret: Optional[bool] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused absmax+quant (ref: quantize.cu).  Same contract as
    ops.quantizer.quantize_int8."""
    n = x.size
    nb = n // block
    if interpret is None:
        if not _on_tpu():  # off-TPU the interpret path is ~3x the jnp one
            return quantize_int8(x, block)
        interpret = False
    if n % block != 0 or not _grid_ok(nb, block):
        return quantize_int8(x, block)
    xb = x.reshape(nb, block)
    q, s = pl.pallas_call(
        _q8_kernel,
        grid=(nb // ROWS, ),
        in_specs=[pl.BlockSpec((ROWS, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, LANE), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, block), jnp.int8),
            jax.ShapeDtypeStruct((nb, LANE), jnp.float32),
        ],
        interpret=interpret,
    )(xb)
    return q, s[:, 0]


def dequantize_int8_pallas(q, scale, shape, interpret: Optional[bool] = None) -> jnp.ndarray:
    nb, block = q.shape
    if interpret is None:
        if not _on_tpu():
            return dequantize_int8(q, scale, shape)
        interpret = False
    if not _grid_ok(nb, block):
        return dequantize_int8(q, scale, shape)
    s = jnp.broadcast_to(scale[:, None], (nb, LANE)).astype(jnp.float32)
    out = pl.pallas_call(
        _dq8_kernel,
        grid=(nb // ROWS, ),
        in_specs=[
            pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, LANE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, block), jnp.float32),
        interpret=interpret,
    )(q, s)
    return out.reshape(shape)


def quantize_int4_pallas(x, block: int = 256, interpret: Optional[bool] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fused absmax+quant+nibble-pack (ref: quantize_intX.cu)."""
    n = x.size
    nb = n // block
    if interpret is None:
        if not _on_tpu():
            return quantize_int4(x, block)
        interpret = False
    if n % block != 0 or block % 2 or not _grid_ok(nb, block, half=True):
        return quantize_int4(x, block)
    xb = x.reshape(nb, block)
    q, s = pl.pallas_call(
        _q4_kernel,
        grid=(nb // ROWS, ),
        in_specs=[pl.BlockSpec((ROWS, block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((ROWS, block // 2), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, LANE), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, block // 2), jnp.uint8),
            jax.ShapeDtypeStruct((nb, LANE), jnp.float32),
        ],
        interpret=interpret,
    )(xb)
    return q, s[:, 0]


def dequantize_int4_pallas(packed, scale, shape, interpret: Optional[bool] = None) -> jnp.ndarray:
    nb, half = packed.shape
    if interpret is None:
        if not _on_tpu():
            return dequantize_int4(packed, scale, shape)
        interpret = False
    if not _grid_ok(nb, half * 2, half=True):
        return dequantize_int4(packed, scale, shape)
    s = jnp.broadcast_to(scale[:, None], (nb, LANE)).astype(jnp.float32)
    out = pl.pallas_call(
        _dq4_kernel,
        grid=(nb // ROWS, ),
        in_specs=[
            pl.BlockSpec((ROWS, half), lambda i: (i, 0)),
            pl.BlockSpec((ROWS, LANE), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((ROWS, half * 2), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nb, half * 2), jnp.float32),
        interpret=interpret,
    )(packed, s)
    return out.reshape(shape)
