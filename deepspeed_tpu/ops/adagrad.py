"""Adagrad (ref: csrc/adagrad/cpu_adagrad.cpp, deepspeed/ops/adagrad)."""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optimizer import GradientTransformation, add_weight_decay, resolve_lr, tree_zeros_like


class AdagradState(NamedTuple):
    step: jnp.ndarray
    accum: Any


def adagrad(lr=1e-2, eps=1e-10, weight_decay=0.0) -> GradientTransformation:

    def init(params):
        return AdagradState(step=jnp.zeros((), jnp.int32), accum=tree_zeros_like(params, jnp.float32))

    def update(grads, state: AdagradState, params=None):
        lr_v = resolve_lr(lr, state.step + 1)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        g32 = add_weight_decay(g32, params, weight_decay)
        accum = jax.tree.map(lambda a, g: a + jnp.square(g), state.accum, g32)
        updates = jax.tree.map(lambda g, a: -lr_v * g / (jnp.sqrt(a) + eps), g32, accum)
        return updates, AdagradState(step=state.step + 1, accum=accum)

    return GradientTransformation(init, update)


class SGDState(NamedTuple):
    momentum: Any


def sgd(lr=1e-2, momentum=0.0, weight_decay=0.0, nesterov=False) -> GradientTransformation:

    def init(params):
        return SGDState(momentum=tree_zeros_like(params, jnp.float32) if momentum else ())

    def update(grads, state: SGDState, params=None):
        lr_v = resolve_lr(lr, 0)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        g32 = add_weight_decay(g32, params, weight_decay)
        if momentum:
            buf = jax.tree.map(lambda b, g: momentum * b + g, state.momentum, g32)
            eff = jax.tree.map(lambda g, b: g + momentum * b, g32, buf) if nesterov else buf
            return jax.tree.map(lambda e: -lr_v * e, eff), SGDState(momentum=buf)
        return jax.tree.map(lambda g: -lr_v * g, g32), state

    return GradientTransformation(init, update)
