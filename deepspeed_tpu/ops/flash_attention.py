"""Pallas TPU flash attention — forward AND backward kernels.

TPU-native replacement for the reference's fused attention kernels
(ref: csrc/transformer/inference softmax/attention kernels and the
FlashAttention integration the reference defers to, e.g.
deepspeed/sequence/fpdt_layer.py:510 which assumes a flash kernel).

Forward: online-softmax tiling — grid over (batch*heads, q-blocks,
kv-blocks) with running max / normaliser / accumulator carried in VMEM
scratch across the kv-block (innermost, "arbitrary") grid dimension; causal
blocks above the diagonal are skipped entirely.  The kernel also emits the
per-row logsumexp so the backward never re-runs the softmax reduction.

Backward: the standard two-kernel FlashAttention-2 split —
  * dq kernel: grid (B*H, q-blocks, kv-blocks), dq accumulated in VMEM over
    the inner kv sweep;
  * dk/dv kernel: grid (B*H, kv-blocks, q-blocks), dk & dv accumulated in
    VMEM over the inner q sweep;
both recompute p = exp(s - lse) per tile from the saved lse (O(S) residuals,
never the [S, S] score matrix), and delta = rowsum(do * o) per tile from the
o/do blocks already resident in VMEM (cheaper than DMA'ing a lane-broadcast
[BH, S, 128] delta input, which at head_dim 64 is twice the bytes of the o
tile).  This replaces the old jnp-reference recompute fallback whose O(S^2)
materialization erased the kernel's training value.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
LANE = 128  # TPU lane width: per-row scalars are stored lane-broadcast


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest, scale, causal, block_q,
                      block_k, kv_blocks):
    lse_ref = rest[0] if len(rest) == 4 else None
    m_scr, l_scr, acc_scr = rest[-3:]
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)  # [bk, d]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    if causal:
        # skip kv-blocks entirely above the diagonal: compute only when the
        # LAST q row of this block can see the FIRST key of the kv block
        pl.when(iq * block_q + block_q - 1 >= ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(ik == kv_blocks - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        if lse_ref is not None:
            # TPU tiling needs the last two block dims (8, 128)-aligned, so
            # the per-row scalar is broadcast across a 128-wide lane dim
            # (same trick as jax's bundled TPU flash kernel's l/m outputs)
            lse_ref[0] = jnp.broadcast_to(m_scr[:] + jnp.log(l), lse_ref[0].shape)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret, emit_lse=True):
    # q, k, v: [BH, S, D] → (o [BH, S, D], lse [BH, S, LANE] | None).
    # emit_lse=False (pure-inference primal) skips the lse output entirely —
    # at head_dim 128 it would otherwise double the kernel's HBM writes.
    bh, sq, d = q.shape
    _, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    kv_blocks = sk // block_k
    scale = 1.0 / (d**0.5)

    grid = (bh, sq // block_q, kv_blocks)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                               kv_blocks=kv_blocks)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))] + ([
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0))] if emit_lse else []),
        out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype)] + ([
            jax.ShapeDtypeStruct((bh, sq, LANE), jnp.float32)] if emit_lse else []),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return (out[0], out[1]) if emit_lse else (out[0], None)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, dq_scr, *, scale, causal,
                         block_q, block_k, kv_blocks):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)      # [bq, d]
        k = k_ref[0].astype(jnp.float32)      # [bk, d]
        v = v_ref[0].astype(jnp.float32)      # [bk, d]
        do = do_ref[0].astype(jnp.float32)    # [bq, d]
        o = o_ref[0].astype(jnp.float32)      # [bq, d]
        lse = lse_ref[0][:, :1]               # [bq, 1] (lane-broadcast store)
        delta = jnp.sum(do * o, axis=1, keepdims=True)  # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                  # [bq, bk]
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(iq * block_q + block_q - 1 >= ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(ik == kv_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                          scale, causal, block_q, block_k, q_blocks):
    ik = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)      # [bq, d]
        k = k_ref[0].astype(jnp.float32)      # [bk, d]
        v = v_ref[0].astype(jnp.float32)      # [bk, d]
        do = do_ref[0].astype(jnp.float32)    # [bq, d]
        o = o_ref[0].astype(jnp.float32)      # [bq, d]
        lse = lse_ref[0][:, :1]               # [bq, 1] (lane-broadcast store)
        delta = jnp.sum(do * o, axis=1, keepdims=True)  # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        p = jnp.exp(s - lse)                  # [bq, bk]
        # dv += pᵀ @ do
        dv_scr[:] += jax.lax.dot_general(p, do, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                                 preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - delta) * scale
        # dk += dsᵀ @ q
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    if causal:
        pl.when(iq * block_q + block_q - 1 >= ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(iq == q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, causal, block_q, block_k, interpret):
    # all [BH, S, D] (lse [BH, S]) → dq, dk, dv
    bh, sq, d = q.shape
    _, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    kv_blocks = sk // block_k
    q_blocks = sq // block_q
    scale = 1.0 / (d**0.5)

    dq_kernel = functools.partial(_flash_bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
                                  block_k=block_k, kv_blocks=kv_blocks)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, o, do, lse)

    dkv_kernel = functools.partial(_flash_bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
                                   block_k=block_k, q_blocks=q_blocks)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, kv_blocks, q_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, LANE), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v, o, do, lse)
    return dq, dk, dv


def _to_bhsd(x, b, h, s, d):
    return x.transpose(0, 2, 1, 3).reshape(b * h, s, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, causal, block_q, block_k, interpret, emit_lse=False)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret, emit_lse=True):
    # [B, S, H, D] layout in, kernels run on [B*H, S, D]
    b, sq, h, d = q.shape
    _, sk, hk, _ = k.shape
    rep = h // hk
    if hk != h:
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = _to_bhsd(q, b, h, sq, d)
    kt = _to_bhsd(k, b, h, sk, d)
    vt = _to_bhsd(v, b, h, sk, d)
    out, lse = _flash_fwd(qt, kt, vt, causal=causal, block_q=block_q, block_k=block_k, interpret=interpret,
                          emit_lse=emit_lse)
    if emit_lse:
        # named so remat policies can SAVE the kernel outputs (see
        # models/llama._resolve_remat_policy 'flash_saveable'): without
        # this, per-block jax.checkpoint re-runs the forward kernel in the
        # backward before the dq/dkv kernels — three attention passes
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
    res = (qt, kt, vt, out, lse, (b, sq, sk, h, hk, d))
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3), res


def _bwd(causal, block_q, block_k, interpret, res, g):
    qt, kt, vt, out, lse, (b, sq, sk, h, hk, d) = res
    do = _to_bhsd(g, b, h, sq, d)
    dq, dk, dv = _flash_bwd(qt, kt, vt, out, lse, do, causal=causal, block_q=block_q, block_k=block_k,
                            interpret=interpret)
    dq = dq.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    dk = dk.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    dv = dv.reshape(b, h, sk, d).transpose(0, 2, 1, 3)
    if hk != h:
        rep = h // hk
        # sum the grads of the repeated kv heads back onto the real ones
        dk = dk.reshape(b, sk, hk, rep, d).sum(axis=3)
        dv = dv.reshape(b, sk, hk, rep, d).sum(axis=3)
    return dq, dk, dv


def _flash_fwd_with_res(q, k, v, causal, block_q, block_k, interpret):
    return _fwd(q, k, v, causal, block_q, block_k, interpret)


_flash_attention.defvjp(_flash_fwd_with_res, _bwd)


def flash_attention(q,
                    k,
                    v,
                    *,
                    causal: bool = True,
                    segment_ids=None,
                    sliding_window: int = 0,
                    block_q: int = 256,
                    block_k: int = 256,
                    interpret: Optional[bool] = None):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    GQA (fewer kv heads) handled by head repetition (grads reduced back in
    the vjp).  ``segment_ids``/``sliding_window`` fall back to the chunked
    jnp path (packed-sequence masking in-kernel is a follow-up).
    """
    if segment_ids is not None or (sliding_window and sliding_window > 0):
        from ..models.llama import chunked_attention
        return chunked_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                 sliding_window=sliding_window)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)
