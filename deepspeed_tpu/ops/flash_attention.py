"""Pallas TPU flash attention — forward AND backward kernels.

TPU-native replacement for the reference's fused attention kernels
(ref: csrc/transformer/inference softmax/attention kernels and the
FlashAttention integration the reference defers to, e.g.
deepspeed/sequence/fpdt_layer.py:510 which assumes a flash kernel).

Forward: online-softmax tiling over a scalar-prefetched lower-triangular
block table (see the design banner below) with running max / normaliser /
accumulator carried in VMEM scratch across the innermost ("arbitrary")
grid dimension.  The kernel also emits the per-row logsumexp so the
backward never re-runs the softmax reduction.

Backward: the standard two-kernel FlashAttention-2 split — a dq kernel
sweeping kv blocks per q row, and a dk/dv kernel sweeping q blocks per kv
column; both recompute p = exp(s - lse) per tile from the saved lse (O(S)
residuals, never the [S, S] score matrix), and delta = rowsum(do · o) per
tile from the o/do blocks already resident in VMEM.

All matmuls feed the MXU bf16 operands with f32 accumulation — measured
0.59 vs 0.37 step MFU at B8/S1024/H12/D64 against the pre-rewrite kernels
that cast to f32 first and ran a dense grid over transposed [B·H, S, D]
copies.
"""

import functools
import math
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)
LANE = 128  # TPU lane width: per-row scalars are stored lane-broadcast


# ---------------------------------------------------------------------------
# v2 kernels: transpose-free packed layout + triangular grid.
#
# The model's natural activation layout is [B, S, H·D] (what the qkv
# projections write and what o_proj reads).  v1 transposed to [B·H, S, D]
# at every kernel entry/exit — 8 HBM-round-trip transposes per layer
# counting the backward.  v2 never transposes: the kernels index head h's
# column slice directly out of the packed [B, S, H·D] array via BlockSpec
# index maps (a reshape [B,S,H,D]→[B,S,H·D] is a free bitcast).  GQA is
# NATIVE (round 4): kv stays packed at its real [B, S, HK·D] width and the
# head grid iterates over kv-head groups — exploiting that the rep query
# heads sharing kv head g are CONTIGUOUS in the packed layout (q head i
# attends kv head i // rep), so one kv block of Pk heads pairs with one q
# block of P = Pk·rep heads at packed offsets hh·Pk·d / hh·P·d.  No
# repeated-KV materialization (at Llama-3-8B's 32q/8kv the repeat cost 4×
# KV HBM traffic), and the dk/dv kernel group-sums the rep query heads'
# contributions in VMEM scratch instead of a post-hoc reshape-sum.
#
# For causal masks the (q-block, kv-block) pairs are flattened into a
# scalar-prefetched lower-triangular table, so blocks above the diagonal
# are neither computed NOR DMA'd — the v1 grid fetched k/v for every
# skipped block, ~37% wasted bandwidth at S=1024 with 256-blocks.  The
# table also marks which blocks straddle the diagonal (see _mask_if_diag
# for why the mask still runs unconditionally).
# ---------------------------------------------------------------------------


def _tri_table(nq, nk, bq, bk, causal, transpose=False, q_offset=0):
    """Flattened block schedule. Rows: 0=iq, 1=ik, 2=first, 3=last, 4=diag.

    ``transpose=False``: row-major sweep (for each q block, its admitted kv
    blocks) — the fwd/dq accumulation order.  ``transpose=True``:
    column-major (for each kv block, its admitted q blocks) — the dk/dv
    order.  first/last flag the accumulation-window boundaries in either
    order.  ``q_offset`` (static) shifts the queries' GLOBAL positions:
    query row r sits at position q_offset + r — the FPDT staged path runs
    one triangular kernel call per (q group x kv prefix) with the group's
    offset, keeping causality exact without a merge pass."""
    import numpy as np
    cols = []
    if not transpose:
        for i in range(nq):
            hi = min(nk, -(-(q_offset + (i + 1) * bq) // bk)) if causal else nk
            for j in range(hi):
                diag = 1 if (causal and (j + 1) * bk - 1 > q_offset + i * bq) else 0
                cols.append((i, j, 1 if j == 0 else 0, 1 if j == hi - 1 else 0, diag))
    else:
        for j in range(nk):
            # clamp so every kv column gets ≥1 entry even when the whole
            # column sits above the causal diagonal (sk > sq): the lone
            # visited block is then fully masked, p ≡ 0, and the dk/dv
            # output block is correctly written as zeros instead of left
            # uninitialized
            lo = min(max(0, (j * bk - q_offset) // bq), nq - 1) if causal else 0
            rows = list(range(lo, nq))
            for n, i in enumerate(rows):
                diag = 1 if (causal and (j + 1) * bk - 1 > q_offset + i * bq) else 0
                cols.append((i, j, 1 if n == 0 else 0, 1 if n == len(rows) - 1 else 0, diag))
    tab = np.asarray(cols, dtype=np.int32).T  # [5, T]
    return tab


def _mask_if_diag(s, tab_ref, t, bq, bk, q_offset=0):
    """Causal mask, no-op'd via the table's diag flag for fully-visible
    blocks.  Measured on v5e: a real lax.cond branch around the masking
    costs ~13% step time (78 vs 69 ms at bench shapes) — the branch breaks
    Mosaic's software pipelining — so the select runs unconditionally and
    the diag flag just widens ``keep`` to all-true."""
    qpos = q_offset + tab_ref[0, t] * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    kpos = tab_ref[1, t] * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    keep = (qpos >= kpos) | (tab_ref[4, t] == 0)
    return jnp.where(keep, s, DEFAULT_MASK_VALUE)


def _gqa_native_ok(d, h, hk):
    """GQA-native blocks put all rep = h//hk query heads sharing a kv block
    into ONE invocation, so scratch and q/o/lse blocks scale with P·d.
    Mainstream GQA (rep ≤ 8) fits easily; MQA-extreme shapes (e.g. Falcon's
    71q/1kv) would blow VMEM — those fall back to repeated KV.  Judged on
    the NARROWEST tile-legal width (the packing heuristic can always fall
    back to it)."""
    rep = h // hk
    min_legal = min(p for p in range(1, hk + 1)
                    if hk % p == 0 and ((p * d) % LANE == 0 or p == hk))
    # ≈2 MB f32 accumulator scratch at bq=512, plus three P-wide q/o/do
    # blocks and a P-wide lse block in the backward — mainstream GQA
    # (rep ≤ 8 at d=128) stays native, Falcon-style 71q/1kv falls back
    return min_legal * rep * d <= 1024


# Widest packed block (query heads x head_dim lanes) the packing heuristic
# targets for SUB-LANE head dims (d < 128, where a single head is not
# tile-legal on its own).  r5: the r4 kernels used the MINIMAL tile-legal
# width (2 heads at d=64), leaving the grid many small steps.  Measured on
# v5e at bench shapes (B24 S1024 H12 D64, fwd+bwd, dispatch amortized
# in-program): Pk=2 8.22 ms, Pk=4 7.86, Pk=6 7.77 (-5.5%), Pk=12 OOMs
# scoped VMEM (17.2M > 16M limit) and Pk=12@bq256 8.27.  384 lanes → Pk=6
# at d=64.  Lane-aligned head dims (d % 128 == 0, e.g. d=128) bypass the
# target entirely and keep their measured r4 geometry Pk=1 — widening them
# to Pk=2/3 is an UNMEASURED shape class (and the in-kernel head loop's
# scratch re-OOMs well before the wider block pays off).
PACK_TARGET = int(os.environ.get("DS_FLASH_PACK_TARGET", "384"))


def _pack_width(d, h, rep=1):
    """KV heads per block.  The packed minor dim must be tile-legal: a
    multiple of the 128-lane width (or ALL heads — a block equal to the
    full array minor dim is always accepted).  Lane-aligned head dims take
    the Pk=1 fast path: one head is already tile-legal, and that is the
    geometry every d=128 measurement (r4/r5) was taken at — the PACK_TARGET
    widening below is only measured for sub-lane dims.  Among the legal
    sub-lane widths, take the LARGEST whose query-side lane width
    (rep x kv heads x d) stays within PACK_TARGET — per-grid-step work
    scales with the width while per-step overhead is fixed."""
    if d % LANE == 0:
        return 1
    legal = [p for p in range(1, h + 1)
             if h % p == 0 and ((p * d) % LANE == 0 or p == h)]
    fitting = [p for p in legal if p * rep * d <= PACK_TARGET]
    return max(fitting) if fitting else min(legal)


def _fwd2_kernel(tab_ref, q_ref, k_ref, v_ref, o_ref, *rest, scale, bq, bk, P, d, rep, q_offset):
    lse_ref = rest[0] if len(rest) % 3 == 1 else None
    scr = rest[1:] if lse_ref is not None else rest
    ms, ls, accs = scr[:P], scr[P:2 * P], scr[2 * P:3 * P]
    t = pl.program_id(2)

    @pl.when(tab_ref[2, t] == 1)
    def _init():
        for p in range(P):
            ms[p][:] = jnp.full_like(ms[p], -jnp.inf)
            ls[p][:] = jnp.zeros_like(ls[p])
            accs[p][:] = jnp.zeros_like(accs[p])

    for pk in range(P // rep):  # kv heads in this block
        # operands stay in their storage dtype (bf16): the MXU takes bf16
        # inputs at full rate with f32 accumulation — casting to f32 first
        # runs the matmuls at ~1/8 MXU throughput
        k = k_ref[0, :, pk * d:(pk + 1) * d]  # [bk, d]
        v = v_ref[0, :, pk * d:(pk + 1) * d]  # [bk, d]
        for r in range(rep):  # query heads sharing kv head pk
            p = pk * rep + r
            q = q_ref[0, :, p * d:(p + 1) * d]  # [bq, d]
            s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            s = _mask_if_diag(s, tab_ref, t, bq, bk, q_offset)
            m_prev = ms[p][:]
            l_prev = ls[p][:]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            pr = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            ls[p][:] = alpha * l_prev + jnp.sum(pr, axis=1, keepdims=True)
            accs[p][:] = accs[p][:] * alpha + jax.lax.dot_general(
                pr.astype(v.dtype), v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
            ms[p][:] = m_new

    @pl.when(tab_ref[3, t] == 1)
    def _finalize():
        for p in range(P):
            l = jnp.maximum(ls[p][:], 1e-30)
            o_ref[0, :, p * d:(p + 1) * d] = (accs[p][:] / l).astype(o_ref.dtype)
            if lse_ref is not None:
                lse_ref[0, p] = jnp.broadcast_to(ms[p][:] + jnp.log(l),
                                                 lse_ref[0, p].shape).astype(lse_ref.dtype)


def _flash_fwd2(q, k, v, *, h, hk, causal, block_q, block_k, interpret, emit_lse=True, q_offset=0):
    # q [B, Sq, H·D], k/v [B, Sk, HK·D] (GQA-native: kv at its real width)
    # → o [B, Sq, H·D], lse [B, H, Sq, LANE]
    b, sq, hd = q.shape
    _, sk, _ = k.shape
    d = hd // h
    rep = h // hk
    Pk = _pack_width(d, hk, rep)  # kv heads per block (tile-legal kv minor dim)
    P = Pk * rep  # query heads per block — contiguous in the packed layout
    # clamp to a divisor: gcd keeps blocks maximal for seq lens that are
    # 128-multiples but not block-multiples (e.g. sq=768 with block 512 → 256)
    bq = math.gcd(min(block_q, sq), sq)
    bk = math.gcd(min(block_k, sk), sk)
    assert sq % bq == 0 and sk % bk == 0, (sq, sk, bq, bk)
    assert h % P == 0 and hk % Pk == 0, (h, hk, P, Pk)
    nq, nk = sq // bq, sk // bk
    scale = 1.0 / (d**0.5)
    tab = _tri_table(nq, nk, bq, bk, causal, q_offset=q_offset)
    grid = (b, hk // Pk, tab.shape[1])

    kernel = functools.partial(_fwd2_kernel, scale=scale, bq=bq, bk=bk, P=P, d=d, rep=rep,
                               q_offset=q_offset)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh)),
            pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
            pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
        ],
        out_specs=[pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh))] + ([
            pl.BlockSpec((1, P, bq, LANE), lambda b, hh, t, tab: (b, hh, tab[0, t], 0))] if emit_lse else []),
        scratch_shapes=([pltpu.VMEM((bq, 1), jnp.float32)] * P +
                        [pltpu.VMEM((bq, 1), jnp.float32)] * P +
                        [pltpu.VMEM((bq, d), jnp.float32)] * P),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        # lse stored in the INPUT dtype: the lane-broadcast layout makes it
        # the LARGEST kernel operand (B·H·S·128 — written once, re-read by
        # BOTH backward kernels).  bf16 runs halve that traffic (lse error
        # ~2⁻⁹·|lse| scales p by ≲1.5%, comparable to the bf16 dot noise
        # already present); f32 runs keep f32 lse and f32-grade grads
        out_shape=[jax.ShapeDtypeStruct((b, sq, hd), q.dtype)] + ([
            jax.ShapeDtypeStruct((b, h, sq, LANE),
                                 jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32)]
            if emit_lse else []),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tab, q, k, v)
    return (out[0], out[1]) if emit_lse else (out[0], None)


def _bwd2_block(tab_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, *, scale, bq, bk, P, d, p, rep, q_offset):
    """Shared per-(block, sub-head) backward math: returns (pr, ds).

    ``p`` indexes the query head within the block; its kv head is
    ``p // rep`` (GQA-native — kv blocks are Pk = P/rep heads wide)."""
    t = pl.program_id(2)
    pk = p // rep
    # bf16 MXU operands + f32 accumulation throughout (see fwd kernel note)
    q = q_ref[0, :, p * d:(p + 1) * d]
    k = k_ref[0, :, pk * d:(pk + 1) * d]
    v = v_ref[0, :, pk * d:(pk + 1) * d]
    do = do_ref[0, :, p * d:(p + 1) * d]
    o = o_ref[0, :, p * d:(p + 1) * d]
    lse = lse_ref[0, p][:, :1].astype(jnp.float32)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=1, keepdims=True)
    s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = _mask_if_diag(s, tab_ref, t, bq, bk, q_offset)
    pr = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1, ), (1, )), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = pr * (dp - delta) * scale
    return q, k, do, pr.astype(v.dtype), ds.astype(v.dtype)


def _dq2_kernel(tab_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref, *scr,
                scale, bq, bk, P, d, rep, q_offset):
    t = pl.program_id(2)

    @pl.when(tab_ref[2, t] == 1)
    def _init():
        for p in range(P):
            scr[p][:] = jnp.zeros_like(scr[p])

    for p in range(P):
        _, k, _, _, ds = _bwd2_block(tab_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                                     scale=scale, bq=bq, bk=bk, P=P, d=d, p=p, rep=rep,
                                     q_offset=q_offset)
        scr[p][:] += jax.lax.dot_general(ds, k, (((1, ), (0, )), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(tab_ref[3, t] == 1)
    def _finalize():
        for p in range(P):
            dq_ref[0, :, p * d:(p + 1) * d] = scr[p][:].astype(dq_ref.dtype)


def _dkv2_kernel(tab_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dk_ref, dv_ref, *scr,
                 scale, bq, bk, P, d, rep, q_offset):
    t = pl.program_id(2)
    Pk = P // rep
    dk_scr, dv_scr = scr[:Pk], scr[Pk:]

    @pl.when(tab_ref[2, t] == 1)
    def _init():
        for pk in range(Pk):
            dk_scr[pk][:] = jnp.zeros_like(dk_scr[pk])
            dv_scr[pk][:] = jnp.zeros_like(dv_scr[pk])

    # the rep query heads sharing a kv head accumulate into ONE dk/dv
    # scratch — the GQA group-sum happens in VMEM, not as a post-pass
    for p in range(P):
        pk = p // rep
        q, _, do, pr, ds = _bwd2_block(tab_ref, q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                                       scale=scale, bq=bq, bk=bk, P=P, d=d, p=p, rep=rep,
                                       q_offset=q_offset)
        dv_scr[pk][:] += jax.lax.dot_general(pr, do, (((0, ), (0, )), ((), ())),
                                             preferred_element_type=jnp.float32)
        dk_scr[pk][:] += jax.lax.dot_general(ds, q, (((0, ), (0, )), ((), ())),
                                             preferred_element_type=jnp.float32)

    @pl.when(tab_ref[3, t] == 1)
    def _finalize():
        for pk in range(Pk):
            dk_ref[0, :, pk * d:(pk + 1) * d] = dk_scr[pk][:].astype(dk_ref.dtype)
            dv_ref[0, :, pk * d:(pk + 1) * d] = dv_scr[pk][:].astype(dv_ref.dtype)


def _flash_bwd2(q, k, v, o, lse, do, *, h, hk, causal, block_q, block_k, interpret, q_offset=0):
    # packed q/o/do [B, Sq, H·D], k/v [B, Sk, HK·D] (GQA-native); dk/dv
    # returned at the real HK width — the group-sum over the rep query
    # heads sharing a kv head happens inside the dkv kernel's scratch
    b, sq, hd = q.shape
    _, sk, _ = k.shape
    d = hd // h
    rep = h // hk
    Pk = _pack_width(d, hk, rep)
    P = Pk * rep
    # clamp to a divisor: gcd keeps blocks maximal for seq lens that are
    # 128-multiples but not block-multiples (e.g. sq=768 with block 512 → 256)
    bq = math.gcd(min(block_q, sq), sq)
    bk = math.gcd(min(block_k, sk), sk)
    nq, nk = sq // bq, sk // bk
    scale = 1.0 / (d**0.5)

    def specs(bq, bk):
        return [
            pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh)),
            pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
            pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
            pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh)),
            pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh)),
            pl.BlockSpec((1, P, bq, LANE), lambda b, hh, t, tab: (b, hh, tab[0, t], 0)),
        ]

    tab_r = _tri_table(nq, nk, bq, bk, causal, q_offset=q_offset)
    dq = pl.pallas_call(
        functools.partial(_dq2_kernel, scale=scale, bq=bq, bk=bk, P=P, d=d, rep=rep,
                          q_offset=q_offset),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hk // Pk, tab_r.shape[1]),
            in_specs=specs(bq, bk),
            out_specs=pl.BlockSpec((1, bq, P * d), lambda b, hh, t, tab: (b, tab[0, t], hh)),
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)] * P,
        ),
        out_shape=jax.ShapeDtypeStruct((b, sq, hd), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tab_r, q, k, v, o, do, lse)

    tab_c = _tri_table(nq, nk, bq, bk, causal, transpose=True, q_offset=q_offset)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv2_kernel, scale=scale, bq=bq, bk=bk, P=P, d=d, rep=rep,
                          q_offset=q_offset),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b, hk // Pk, tab_c.shape[1]),
            in_specs=specs(bq, bk),
            out_specs=[
                pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
                pl.BlockSpec((1, bk, Pk * d), lambda b, hh, t, tab: (b, tab[1, t], hh)),
            ],
            scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32)] * 2 * Pk,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b, sk, hk * d), k.dtype),
            jax.ShapeDtypeStruct((b, sk, hk * d), v.dtype),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tab_c, q, k, v, o, do, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret, q_offset=0):
    out, _ = _fwd(q, k, v, causal, block_q, block_k, interpret, q_offset, emit_lse=False)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret, q_offset=0, emit_lse=True):
    # [B, S, H, D] in/out; kernels run on the packed [B, S, H·D] view
    # (a FREE reshape — same memory layout, no transpose).  GQA-native:
    # kv stays at its real HK width — the kernels pair each kv-head block
    # with the contiguous run of query heads that share it (no repeated-KV
    # materialization; 4× less KV HBM traffic at Llama-3-8B's 32q/8kv)
    b, sq, h, d = q.shape
    _, sk, hk_real, _ = k.shape
    assert h % hk_real == 0, f"query heads {h} not a multiple of kv heads {hk_real}"
    hk = hk_real
    if hk != h and not _gqa_native_ok(d, h, hk):
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
        hk = h
    qp = q.reshape(b, sq, h * d)
    kp = k.reshape(b, sk, hk * d)
    vp = v.reshape(b, sk, hk * d)
    out, lse = _flash_fwd2(qp, kp, vp, h=h, hk=hk, causal=causal, block_q=block_q,
                           block_k=block_k, interpret=interpret, emit_lse=emit_lse,
                           q_offset=q_offset)
    if emit_lse:
        # named so remat policies can SAVE the kernel outputs (see
        # models/llama._resolve_remat_policy 'flash_saveable'): without
        # this, per-block jax.checkpoint re-runs the forward kernel in the
        # backward before the dq/dkv kernels — three attention passes
        from jax.ad_checkpoint import checkpoint_name
        out = checkpoint_name(out, "flash_out")
        lse = checkpoint_name(lse, "flash_lse")
    res = (qp, kp, vp, out, lse, (b, sq, sk, h, hk, hk_real, d))
    return out.reshape(b, sq, h, d), res


def _bwd(causal, block_q, block_k, interpret, q_offset, res, g):
    qp, kp, vp, out, lse, (b, sq, sk, h, hk, hk_real, d) = res
    do = g.reshape(b, sq, h * d)
    dq, dk, dv = _flash_bwd2(qp, kp, vp, out, lse, do, h=h, hk=hk, causal=causal,
                             block_q=block_q, block_k=block_k, interpret=interpret,
                             q_offset=q_offset)
    dq = dq.reshape(b, sq, h, d)
    dk = dk.reshape(b, sk, hk, d)
    dv = dv.reshape(b, sk, hk, d)
    if hk != hk_real:
        # VMEM-cap fallback ran the kernels over repeated KV: group-sum the
        # per-query-head kv grads back onto the real kv heads
        rep = hk // hk_real
        dk = dk.reshape(b, sk, hk_real, rep, d).sum(axis=3)
        dv = dv.reshape(b, sk, hk_real, rep, d).sum(axis=3)
    # otherwise dk/dv are already at the real HK width — the GQA group-sum
    # happened inside the dkv kernel's scratch accumulation
    return dq, dk, dv


def _flash_fwd_with_res(q, k, v, causal, block_q, block_k, interpret, q_offset=0):
    return _fwd(q, k, v, causal, block_q, block_k, interpret, q_offset)


_flash_attention.defvjp(_flash_fwd_with_res, _bwd)


def _flash_sharded(q, k, v, causal, block_q, block_k, interpret, mesh, q_offset=0):
    """Run the kernels inside shard_map over the governing (trace) mesh.

    Mosaic custom calls cannot be auto-partitioned by GSPMD — a multi-device
    jit containing a Pallas call must wrap it in shard_map.  Batch shards
    over the data axes; heads shard over the seq/tensor axes when divisible
    (the layout Ulysses' all-to-all and AutoTP establish); a non-divisible
    dim replicates (correct, just not distributed)."""
    from jax.sharding import PartitionSpec as P

    from ..comm.mesh import BATCH_AXES, SEQ_AXIS, TENSOR_AXIS
    b, _, h, _ = q.shape
    hk = k.shape[2]
    batch_axes = tuple(a for a in BATCH_AXES if mesh.shape.get(a, 1) > 1)
    nb = math.prod(mesh.shape[a] for a in batch_axes) if batch_axes else 1
    if nb > 1 and b % nb:
        batch_axes = ()
    head_axes = tuple(a for a in (SEQ_AXIS, TENSOR_AXIS) if mesh.shape.get(a, 1) > 1)
    nh = math.prod(mesh.shape[a] for a in head_axes) if head_axes else 1
    if nh > 1 and (h % nh or hk % nh):
        head_axes = ()
    spec = P(batch_axes or None, None, head_axes or None, None)
    fn = jax.shard_map(
        lambda q_, k_, v_: _flash_attention(q_, k_, v_, causal, block_q, block_k, interpret,
                                            q_offset),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        # pallas_call out_shapes carry no varying-mesh-axes annotation
        check_vma=False)
    return fn(q, k, v)


def flash_attention(q,
                    k,
                    v,
                    *,
                    causal: bool = True,
                    segment_ids=None,
                    sliding_window: int = 0,
                    block_q: int = 512,
                    block_k: int = 512,
                    interpret: Optional[bool] = None,
                    q_position_offset: int = 0):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    GQA (fewer kv heads) is kernel-native: kv blocks stay at the real kv
    width and each pairs with the contiguous group of query heads sharing
    it; kv grads are group-summed in kernel scratch (ref: the reference's
    blocked GQA attention, deepspeed/inference/v2/kernels/ragged_ops/).
    ``segment_ids``/``sliding_window`` fall back to the chunked jnp path
    (packed-sequence masking in-kernel is a follow-up).
    """
    if (segment_ids is not None or (sliding_window and sliding_window > 0)
            or q.shape[1] % LANE != 0 or k.shape[1] % LANE != 0):
        # packed-sequence masking in-kernel is a follow-up; ragged lengths
        # would force sub-128 blocks that violate TPU tiling
        if q_position_offset:
            raise ValueError("q_position_offset requires 128-aligned seq lens and no "
                             "segment/window masks (the chunked fallback has no offset)")
        from ..models.llama import chunked_attention
        return chunked_attention(q, k, v, causal=causal, segment_ids=segment_ids,
                                 sliding_window=sliding_window)
    from ..comm.mesh import get_trace_mesh, in_manual_mesh
    if interpret is None:
        # resolve against the GOVERNING mesh, not the local devices: an AOT
        # compile for an offline TPU topology from a CPU-only host must
        # lower the real kernels, not interpret mode
        tm = get_trace_mesh()
        dev = tm.devices.flat[0] if tm is not None else jax.devices()[0]
        interpret = getattr(dev, "platform", "") != "tpu"
    if isinstance(q, jax.core.Tracer) and not in_manual_mesh():
        mesh = get_trace_mesh()
        if mesh is not None and mesh.size > 1:
            return _flash_sharded(q, k, v, causal, block_q, block_k, interpret, mesh,
                                  q_position_offset)
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret, q_position_offset)
