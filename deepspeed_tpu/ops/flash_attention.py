"""Pallas TPU flash attention.

TPU-native replacement for the reference's fused attention kernels
(ref: csrc/transformer/inference softmax/attention kernels and the
FlashAttention integration the reference defers to).  Online-softmax tiling:
grid over (batch*heads, q-blocks, kv-blocks) with running max / normaliser /
accumulator carried in VMEM scratch across the kv-block (innermost,
"arbitrary") grid dimension.  Causal blocks above the diagonal are skipped
entirely (both the matmuls and the DMA cost is amortised by the grid order).

Training: forward runs the Pallas kernel; backward currently recomputes via
the jnp reference path under ``jax.custom_vjp`` (a dedicated backward kernel
is the planned follow-up — the fwd kernel already gives the decode/eval win
and the fwd-pass memory win).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                      kv_blocks):
    iq = pl.program_id(1)
    ik = pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [bq, d]
        k = k_ref[0].astype(jnp.float32)  # [bk, d]
        v = v_ref[0].astype(jnp.float32)  # [bk, d]
        s = jax.lax.dot_general(q, k, (((1, ), (1, )), ((), ())),
                                preferred_element_type=jnp.float32) * scale  # [bq, bk]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1, ), (0, )), ((), ())), preferred_element_type=jnp.float32)
        m_scr[:] = m_new
        l_scr[:] = l_new

    if causal:
        # skip kv-blocks entirely above the diagonal: compute only when the
        # LAST q row of this block can see the FIRST key of the kv block
        pl.when(iq * block_q + block_q - 1 >= ik * block_k)(_compute)
    else:
        _compute()

    @pl.when(ik == kv_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_scr[:] / jnp.maximum(l_scr[:], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    # q, k, v: [BH, S, D]
    bh, sq, d = q.shape
    _, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    kv_blocks = sk // block_k
    scale = 1.0 / (d**0.5)

    grid = (bh, sq // block_q, kv_blocks)
    kernel = functools.partial(_flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
                               kv_blocks=kv_blocks)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


def _reference(q, k, v, causal):
    from ..models.llama import reference_attention
    return reference_attention(q, k, v, causal=causal)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention(q, k, v, causal, block_q, block_k, interpret):
    # [B, S, H, D] layout in, kernel runs on [B*H, S, D]
    b, sq, h, d = q.shape
    _, sk, hk, _ = k.shape
    if hk != h:
        rep = h // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    out = _flash_fwd(qt, kt, vt, causal=causal, block_q=block_q, block_k=block_k, interpret=interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q_, k_, v_: _reference(q_, k_, v_, causal), q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(q,
                    k,
                    v,
                    *,
                    causal: bool = True,
                    segment_ids=None,
                    block_q: int = 256,
                    block_k: int = 256,
                    interpret: Optional[bool] = None):
    """Flash attention over [batch, seq, heads, head_dim] tensors.

    GQA (fewer kv heads) handled by head repetition.  ``segment_ids`` falls
    back to the reference path (packed-sequence masking lands with the
    dedicated backward kernel).
    """
    if segment_ids is not None:
        from ..models.llama import reference_attention
        return reference_attention(q, k, v, causal=causal, segment_ids=segment_ids)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return _flash_attention(q, k, v, causal, block_q, block_k, interpret)
