"""1-bit optimizer family: OnebitAdam, OnebitLamb, ZeroOneAdam.

Reference: ``deepspeed/runtime/fp16/onebit/{adam.py:14,lamb.py:15,
zoadam.py:14}`` — communication-compressed optimizers: after a full-
precision warmup (``freeze_step``), the variance term is frozen and the
momentum is communicated sign-compressed with error feedback.

TPU-native realisation: under GSPMD/ZeRO the cross-replica gradient mean is
compiler-inserted and optimizer state is already partitioned, so the
*transport* compression lives in ``runtime/comm/compressed.py``
(compressed_allreduce / qgZ all_to_all_quant_reduce, for explicit shard_map
pipelines over DCN).  These transforms reproduce the reference's *numerics*
— frozen variance + error-feedback 1-bit momentum quantization — which is
what determines convergence behaviour; jitted elementwise math takes the
place of the fused CUDA kernels.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .optimizer import GradientTransformation, resolve_lr, tree_zeros_like


def _sign_compress_ef(tensor, error):
    """Error-feedback 1-bit quantization of one tensor (the numerics of
    ref compressed_allreduce steps 1-2, without the wire exchange)."""
    corrected = tensor + error
    scale = jnp.mean(jnp.abs(corrected))
    signs = jnp.sign(corrected)
    signs = jnp.where(signs == 0, 1.0, signs)
    compressed = scale * signs
    return compressed, corrected - compressed


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any


def onebit_adam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                freeze_step: int = 100, compress_fn=None, **_ignored) -> GradientTransformation:
    """ref: runtime/fp16/onebit/adam.py:14 OnebitAdam.

    ``compress_fn(tensor, error) -> (compressed, new_error)`` plugs the
    TRANSPORT in: the default is the local error-feedback sign quantization
    (numerics only); the engine passes the wire-exchanging
    ``runtime/comm/compressed.compressed_allreduce`` bound to the data axis
    when it builds the shard_map training step (ref: the comm_backend
    handles in runtime/fp16/onebit/adam.py:99)."""
    b1, b2 = betas

    def init(params):
        return OnebitAdamState(count=jnp.zeros((), jnp.int32),
                               exp_avg=tree_zeros_like(params, jnp.float32),
                               exp_avg_sq=tree_zeros_like(params, jnp.float32),
                               error=tree_zeros_like(params, jnp.float32))

    def update(grads, state, params):
        count = state.count + 1
        frozen = count > freeze_step  # compression stage

        def upd(g, m, v, e, p):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            # variance frozen after warmup (ref: adam.py exp_avg_sq freeze)
            v_new = jnp.where(frozen, v, b2 * v + (1 - b2) * g * g)
            comp, e_comp = (compress_fn or _sign_compress_ef)(m_new, e)
            m_used = jnp.where(frozen, comp, m_new)
            e_new = jnp.where(frozen, e_comp, e)
            bc1 = 1 - b1**count.astype(jnp.float32)
            bc2 = 1 - b2**count.astype(jnp.float32)
            step = (m_used / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay:
                step = step + weight_decay * p.astype(jnp.float32)
            return -resolve_lr(lr, count) * step, m_used, v_new, e_new

        flat = jax.tree.map(upd, grads, state.exp_avg, state.exp_avg_sq, state.error, params)
        updates = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        m = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        v = jax.tree.map(lambda t: t[2], flat, is_leaf=lambda x: isinstance(x, tuple))
        e = jax.tree.map(lambda t: t[3], flat, is_leaf=lambda x: isinstance(x, tuple))
        return updates, OnebitAdamState(count=count, exp_avg=m, exp_avg_sq=v, error=e)

    return GradientTransformation(init, update)


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any
    var_interval: jnp.ndarray   # current variance-update interval
    var_counter: jnp.ndarray    # steps since last variance update
    var_updates: jnp.ndarray    # number of variance updates so far (bias corr)


def zero_one_adam(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, compress_fn=None,
                  var_allreduce_fn=None,
                  var_freeze_step: int = 100000, var_update_scaler: int = 16,
                  local_step_scaler: int = 32678, local_step_clipper: int = 16,
                  **_ignored) -> GradientTransformation:
    """ref: runtime/fp16/onebit/zoadam.py:14 ZeroOneAdam (0/1 Adam) — the
    variance is updated only at exponentially-spaced intervals (doubling
    every ``var_update_scaler`` updates) until ``var_freeze_step``, and the
    momentum is always error-feedback compressed (no warmup).

    ``var_allreduce_fn(grad) -> global mean grad``: the reference updates
    ``exp_avg_sq`` from the UNCOMPRESSED allreduced gradient on var-interval
    steps (zoadam.py exchanges the raw grad there).  When the wire transport
    is active the engine passes an fp32 pmean here; it runs under
    ``lax.cond`` so the uncompressed exchange is only paid on the
    exponentially-rare var-due steps.  Without it (wire active but no
    allreduce handle) the variance falls back to the gradient reconstructed
    from the post-exchange momentum, (m_t - b1·m_{t-1})/(1-b1) — still
    globally identical across workers, but it folds the sign-quantization /
    error-feedback noise into the squared term, biasing exp_avg_sq upward
    (an ACCEPTED deviation when no uncompressed wire exists)."""
    b1, b2 = betas

    def init(params):
        return ZeroOneAdamState(count=jnp.zeros((), jnp.int32),
                                exp_avg=tree_zeros_like(params, jnp.float32),
                                exp_avg_sq=tree_zeros_like(params, jnp.float32),
                                error=tree_zeros_like(params, jnp.float32),
                                var_interval=jnp.ones((), jnp.int32),
                                var_counter=jnp.zeros((), jnp.int32),
                                var_updates=jnp.zeros((), jnp.int32))

    def update(grads, state, params):
        count = state.count + 1
        frozen = count > var_freeze_step
        var_due = jnp.logical_and(~frozen, state.var_counter + 1 >= state.var_interval)
        new_counter = jnp.where(var_due, 0, state.var_counter + 1)
        # interval doubles after every var_update_scaler VARIANCE UPDATES
        # (not global steps — ref zoadam.py interval policy)
        var_updates = state.var_updates + var_due.astype(jnp.int32)
        grow = jnp.logical_and(var_due, (var_updates % var_update_scaler) == 0)
        new_interval = jnp.where(grow, state.var_interval * 2, state.var_interval)

        def upd(g, m, v, e, p):
            g = g.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            comp, e_new = (compress_fn or _sign_compress_ef)(m_new, e)
            if var_allreduce_fn is not None:
                # reference numerics (zoadam.py): var-due steps use the
                # UNCOMPRESSED allreduced grad.  cond-gated so the fp32
                # exchange only executes on the (exponentially rare) due
                # steps; the false branch's local g is never consumed —
                # v_new selects the old v when ~var_due
                g_var = jax.lax.cond(var_due, var_allreduce_fn, lambda x: x, g)
            elif compress_fn is not None:
                # WIRE transport without an uncompressed allreduce handle:
                # the local grad differs per worker, so a variance update
                # from it would fork exp_avg_sq (and then params) across
                # ranks.  Reconstruct the globally-averaged gradient from
                # the post-exchange momentum — identical on every worker —
                # at the cost of the documented upward sign-noise bias
                g_var = (comp - b1 * m) / (1 - b1)
            else:
                g_var = g
            v_new = jnp.where(var_due, b2 * v + (1 - b2) * g_var * g_var, v)
            bc1 = 1 - b1**count.astype(jnp.float32)
            bc2 = 1 - b2**jnp.maximum(var_updates, 1).astype(jnp.float32)
            step = (comp / bc1) / (jnp.sqrt(v_new / bc2) + eps)
            if weight_decay:
                step = step + weight_decay * p.astype(jnp.float32)
            return -resolve_lr(lr, count) * step, comp, v_new, e_new

        flat = jax.tree.map(upd, grads, state.exp_avg, state.exp_avg_sq, state.error, params)
        pick = lambda i: jax.tree.map(lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), ZeroOneAdamState(count=count, exp_avg=pick(1), exp_avg_sq=pick(2),
                                         error=pick(3), var_interval=new_interval,
                                         var_counter=new_counter, var_updates=var_updates)

    return GradientTransformation(init, update)


class OnebitLambState(NamedTuple):
    count: jnp.ndarray
    exp_avg: any
    exp_avg_sq: any
    error: any
    frozen_ratio: any  # per-tensor trust ratio recorded at freeze


def onebit_lamb(lr=1e-3, betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0, compress_fn=None,
                freeze_step: int = 100, max_coeff: float = 10.0, min_coeff: float = 0.01,
                **_ignored) -> GradientTransformation:
    """ref: runtime/fp16/onebit/lamb.py:15 OnebitLamb — LAMB whose layerwise
    trust ratio is recorded at ``freeze_step`` and reused during the
    compression stage (fresh ratios would need uncompressed norms)."""
    b1, b2 = betas

    def init(params):
        return OnebitLambState(count=jnp.zeros((), jnp.int32),
                               exp_avg=tree_zeros_like(params, jnp.float32),
                               exp_avg_sq=tree_zeros_like(params, jnp.float32),
                               error=tree_zeros_like(params, jnp.float32),
                               frozen_ratio=jax.tree.map(lambda p: jnp.ones((), jnp.float32), params))

    def update(grads, state, params):
        count = state.count + 1
        frozen = count > freeze_step

        def upd(g, m, v, e, p, fr):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1 - b1) * g
            v_new = jnp.where(frozen, v, b2 * v + (1 - b2) * g * g)
            comp, e_comp = (compress_fn or _sign_compress_ef)(m_new, e)
            m_used = jnp.where(frozen, comp, m_new)
            e_new = jnp.where(frozen, e_comp, e)
            bc1 = 1 - b1**count.astype(jnp.float32)
            bc2 = 1 - b2**count.astype(jnp.float32)
            raw = (m_used / bc1) / (jnp.sqrt(v_new / bc2) + eps) + weight_decay * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(raw)
            live_ratio = jnp.clip(jnp.where(u_norm > 0, w_norm / u_norm, 1.0),
                                  min_coeff, max_coeff)
            # record the ratio while uncompressed; reuse it after freeze
            fr_new = jnp.where(frozen, fr, live_ratio)
            ratio = jnp.where(frozen, fr, live_ratio)
            return -resolve_lr(lr, count) * ratio * raw, m_used, v_new, e_new, fr_new

        flat = jax.tree.map(upd, grads, state.exp_avg, state.exp_avg_sq, state.error,
                            params, state.frozen_ratio)
        pick = lambda i: jax.tree.map(lambda t: t[i], flat, is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), OnebitLambState(count=count, exp_avg=pick(1), exp_avg_sq=pick(2),
                                        error=pick(3), frozen_ratio=pick(4))

    return GradientTransformation(init, update)
