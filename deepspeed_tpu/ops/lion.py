"""Lion (ref: csrc/lion/fused_lion*.cu + deepspeed/ops/lion).

sign-of-interpolated-momentum update; decoupled weight decay.
"""

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .optimizer import GradientTransformation, resolve_lr, tree_zeros_like


class LionState(NamedTuple):
    step: jnp.ndarray
    exp_avg: Any


def fused_lion(lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0) -> GradientTransformation:
    b1, b2 = betas

    def init(params):
        return LionState(step=jnp.zeros((), jnp.int32), exp_avg=tree_zeros_like(params, jnp.float32))

    def update(grads, state: LionState, params=None):
        step = state.step + 1
        lr_v = resolve_lr(lr, step)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        updates = jax.tree.map(lambda m_, g: -lr_v * jnp.sign(b1 * m_ + (1 - b1) * g), state.exp_avg, g32)
        if weight_decay > 0.0 and params is not None:
            updates = jax.tree.map(lambda u, p: u - lr_v * weight_decay * p.astype(jnp.float32), updates, params)
        m = jax.tree.map(lambda m_, g: b2 * m_ + (1 - b2) * g, state.exp_avg, g32)
        return updates, LionState(step=step, exp_avg=m)

    return GradientTransformation(init, update)


lion = fused_lion
