"""Op-builder registry.

ref: ``op_builder/__init__.py`` + the ~25 per-op builders (SURVEY §2.6).
On TPU most "ops" are Pallas/XLA modules whose build happens at trace
time; their builders exist for `ds_report` parity and return the Python
module from ``load()``.  Native C++ builders (aio) actually compile.
"""

import importlib
import os

from .builder import AsyncIOBuilder, OpBuilder  # noqa: F401


class PallasOpBuilder(OpBuilder):
    """Builder whose artifact is a Python module of Pallas/XLA kernels
    (ref: SURVEY §2.6 TPU note: builders return Pallas/XLA implementations
    instead of nvcc-compiled modules)."""

    MODULE = None  # dotted path relative to deepspeed_tpu

    def sources(self):
        return []

    def is_installed(self):
        try:
            importlib.import_module(f"deepspeed_tpu.{self.MODULE}")
            return True
        except ImportError:
            return False

    def is_compatible(self):
        # no g++ requirement — the only gate is the BUILD_VAR kill switch
        if self.BUILD_VAR and os.environ.get(self.BUILD_VAR, "1") == "0":
            return False
        return self.is_installed()

    def load(self):
        return importlib.import_module(f"deepspeed_tpu.{self.MODULE}")


class FusedAdamBuilder(PallasOpBuilder):
    """ref: op_builder/fused_adam.py:11 (DS_BUILD_FUSED_ADAM)."""
    BUILD_VAR = "DS_BUILD_FUSED_ADAM"
    NAME = "fused_adam"
    MODULE = "ops.adam"


class CPUAdamBuilder(PallasOpBuilder):
    """ref: op_builder/cpu_adam.py — host-offloaded states use the same
    jitted update, residency is a sharding property."""
    BUILD_VAR = "DS_BUILD_CPU_ADAM"
    NAME = "cpu_adam"
    MODULE = "ops.adam"


class FusedLambBuilder(PallasOpBuilder):
    """ref: op_builder/fused_lamb.py."""
    BUILD_VAR = "DS_BUILD_FUSED_LAMB"
    NAME = "fused_lamb"
    MODULE = "ops.lamb"


class FusedLionBuilder(PallasOpBuilder):
    """ref: op_builder/fused_lion.py."""
    BUILD_VAR = "DS_BUILD_FUSED_LION"
    NAME = "fused_lion"
    MODULE = "ops.lion"


class CPUAdagradBuilder(PallasOpBuilder):
    """ref: op_builder/cpu_adagrad.py."""
    BUILD_VAR = "DS_BUILD_CPU_ADAGRAD"
    NAME = "cpu_adagrad"
    MODULE = "ops.adagrad"


class QuantizerBuilder(PallasOpBuilder):
    """ref: op_builder/quantizer.py (csrc/quantization kernels)."""
    BUILD_VAR = "DS_BUILD_QUANTIZER"
    NAME = "quantizer"
    MODULE = "ops.quantizer"


class FPQuantizerBuilder(PallasOpBuilder):
    """ref: op_builder/fp_quantizer.py (csrc/fp_quantizer) — the e3m2/e5m6
    bit-packing lives in linear/quantization.py."""
    BUILD_VAR = "DS_BUILD_FP_QUANTIZER"
    NAME = "fp_quantizer"
    MODULE = "linear.quantization"


class FlashAttnBuilder(PallasOpBuilder):
    """Pallas flash attention (plays the role of csrc/transformer fused
    attention, SURVEY §2.5)."""
    BUILD_VAR = "DS_BUILD_FLASH_ATTN"
    NAME = "flash_attn"
    MODULE = "ops.flash_attention"


class RaggedOpsBuilder(PallasOpBuilder):
    """ref: op_builder/ragged_ops.py — FastGen paged/ragged decode path."""
    BUILD_VAR = "DS_BUILD_RAGGED_OPS"
    NAME = "ragged_ops"
    MODULE = "ops.paged_attention"


class SparseAttnBuilder(PallasOpBuilder):
    """ref: op_builder/sparse_attn.py — block-sparse attention."""
    BUILD_VAR = "DS_BUILD_SPARSE_ATTN"
    NAME = "sparse_attn"
    MODULE = "ops.sparse_attention"


class RandomLTDBuilder(PallasOpBuilder):
    """ref: op_builder/random_ltd.py — token gather/scatter for random-LTD."""
    BUILD_VAR = "DS_BUILD_RANDOM_LTD"
    NAME = "random_ltd"
    MODULE = "runtime.data_pipeline.data_routing.basic_layer"


# native C++ aio builder gains is_installed for the report
def _aio_is_installed(self):
    return self.so_path().exists()


AsyncIOBuilder.is_installed = _aio_is_installed

ALL_OPS = {
    b.NAME: b
    for b in (AsyncIOBuilder, FusedAdamBuilder, CPUAdamBuilder, FusedLambBuilder, FusedLionBuilder,
              CPUAdagradBuilder, QuantizerBuilder, FPQuantizerBuilder, FlashAttnBuilder, RaggedOpsBuilder,
              SparseAttnBuilder, RandomLTDBuilder)
}


_OP_NAME_ALIASES = {"async_io": "ds_aio"}  # upstream op name → ours


def get_builder(class_name: str):
    """Resolve a builder CLASS by its class name ('AsyncIOBuilder') or op
    name ('ds_aio'; upstream's 'async_io' aliased) — the accelerator
    interface's get_op_builder indirection (ref:
    accelerator/cuda_accelerator.py get_op_builder importing from
    op_builder per vendor dir)."""
    for b in ALL_OPS.values():
        if b.__name__ == class_name:
            return b
    return ALL_OPS.get(_OP_NAME_ALIASES.get(class_name, class_name))
