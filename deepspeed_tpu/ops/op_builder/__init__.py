from .builder import AsyncIOBuilder, OpBuilder  # noqa: F401
