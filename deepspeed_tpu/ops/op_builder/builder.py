"""Op build system — JIT compilation of native (C++) components.

Reference: ``op_builder/builder.py`` (``OpBuilder:117`` with ``sources()``,
``include_paths()``, ``is_compatible()``, ``load()`` → prebuilt import or
``jit_load:542`` via torch's cpp_extension).  Here the native components are
plain C-ABI shared libraries consumed through ctypes (no torch build
machinery): ``load()`` compiles ``sources()`` with g++ into a cached .so
keyed by a source hash, then returns the ctypes CDLL.  Builders for Pallas/
XLA "ops" simply return the Python module implementing them — on TPU the
kernel "build" is XLA compilation at trace time (SURVEY §2.6 TPU note).
"""

import ctypes
import hashlib
import os
import shutil
import subprocess
from pathlib import Path
from typing import List, Optional

from ...utils.logging import logger

_REPO_ROOT = Path(__file__).resolve().parents[2]  # deepspeed_tpu/
DEFAULT_CACHE = os.environ.get("DS_TPU_OP_CACHE",
                               os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu", "ops"))


class OpBuilder:
    """Base builder (ref: op_builder/builder.py:117 OpBuilder)."""

    BUILD_VAR: Optional[str] = None  # e.g. DS_BUILD_AIO — 0 disables
    NAME = "op"

    def sources(self) -> List[str]:
        """C++ sources relative to ``deepspeed_tpu/``."""
        raise NotImplementedError

    def include_paths(self) -> List[str]:
        return []

    def cxx_args(self) -> List[str]:
        return ["-O3", "-std=c++17", "-fPIC", "-shared", "-pthread", "-Wall"]

    def is_compatible(self) -> bool:
        if self.BUILD_VAR and os.environ.get(self.BUILD_VAR, "1") == "0":
            return False
        return shutil.which("g++") is not None

    def absolute_sources(self) -> List[Path]:
        return [(_REPO_ROOT / s) for s in self.sources()]

    def _source_hash(self) -> str:
        h = hashlib.sha256()
        for src in self.absolute_sources():
            h.update(src.read_bytes())
        h.update(" ".join(self.cxx_args()).encode())
        return h.hexdigest()[:16]

    def so_path(self) -> Path:
        return Path(DEFAULT_CACHE) / f"{self.NAME}_{self._source_hash()}.so"

    def jit_load(self) -> Path:
        """ref: builder.py:542 jit_load — compile into the user cache."""
        out = self.so_path()
        if out.exists():
            return out
        out.parent.mkdir(parents=True, exist_ok=True)
        cmd = (["g++"] + self.cxx_args() +
               [f"-I{p}" for p in self.include_paths()] +
               [str(s) for s in self.absolute_sources()] + ["-o", str(out) + ".tmp"])
        logger.info(f"op_builder[{self.NAME}]: {' '.join(cmd)}")
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(f"building {self.NAME} failed:\n{e.stderr}") from e
        os.replace(str(out) + ".tmp", out)
        return out

    def load(self) -> ctypes.CDLL:
        """Compile if needed and dlopen (ref: builder.py:523 load)."""
        if not self.is_compatible():
            raise RuntimeError(f"op {self.NAME} is not compatible on this system "
                               f"(g++ missing or {self.BUILD_VAR}=0)")
        return ctypes.CDLL(str(self.jit_load()))


class AsyncIOBuilder(OpBuilder):
    """ref: op_builder/async_io.py AsyncIOBuilder (BUILD_VAR DS_BUILD_AIO)."""
    BUILD_VAR = "DS_BUILD_AIO"
    NAME = "ds_aio"

    def sources(self):
        return ["csrc/aio/ds_aio.cpp"]
