"""ServingEngine: the SLA-aware frontend over :class:`InferenceEngineV2`.

Reference: FastGen's serving methodology (``blogs/deepspeed-fastgen`` —
Poisson-arrival load, first-token + per-token SLAs) and Orca-style
iteration-level scheduling.  The v2 engine exposes ``put()``/``step()``
over *sequences*; this layer adds what "serving" means:

* a bounded request QUEUE with admission control (reject/backpressure at
  the request boundary instead of crashing mid-step — admission.py);
* FCFS-with-aging ordering, installed into ``SplitFuseScheduler.order_key``
  so step planning follows request priority/arrival, not dict-iteration
  order (priority classes age toward urgent so nothing starves);
* KV-pressure preemption (kv_pressure.py): the youngest sequence is
  evicted — pages released, generated tokens preserved on the request —
  and requeued for recompute-on-resume, instead of the step raising;
* deadlines: expired requests (queued or running) are timed out and their
  capacity reclaimed; goodput counts only deadline-met completions;
* per-request TTFT/TPOT/queue-wait accounting streamed through the
  existing ``monitor`` event surface (``write_events`` tuples), plus
  per-token delivery callbacks as tokens land.

The loop is clock-driven (clock.py): identical code serves wall-clock
traffic and deterministic virtual-clock CPU tests / the load harness.
"""

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..resilience.fault_injection import InjectedCrash
from ..telemetry.step_anatomy import NULL_ANATOMY
from ..telemetry.trace import NULL_TRACER
from ..utils.logging import logger
from .admission import AdmissionConfig, AdmissionController
from .clock import VirtualClock, WallClock  # noqa: F401  (re-exported convenience)
from .kv_pressure import KVPressureManager
from .metrics import ServingStats
from .request import RequestState, ServingRequest


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    admission: AdmissionConfig = AdmissionConfig()
    # deadline policy: True kills expired requests (queued or running) and
    # reclaims their capacity; False lets them finish late (still counted
    # against goodput — they missed the SLA either way)
    kill_on_deadline: bool = True
    # FCFS-with-aging: a request's priority class improves by one full class
    # per ``aging_interval`` seconds waited, so low-priority work cannot
    # starve behind a stream of urgent arrivals.  0 disables aging (pure
    # priority-then-FCFS).
    aging_interval: float = 0.0
    # VirtualClock cost model: seconds one engine step takes, as a function
    # of the planned token count (decodes + prefill chunk tokens).  None →
    # every step costs 1.0 virtual second (pure step-count latency).
    step_cost: Optional[Callable[[int], float]] = None
    # async double-buffered dispatch: each tick completes the PREVIOUS
    # step's readback, then enqueues the next step and returns — so step
    # g+1's host-side work (admission, scheduling, delivery) runs while
    # step g executes on device, blocking only at the sample/accept
    # readback.  Greedy token streams are byte-identical to the serial
    # loop (each request's tokens depend only on its own accepted
    # history); deadline expiry may fire up to one step earlier than the
    # serial loop would, since the overlap window checks deadlines before
    # the in-flight step's tokens fold.
    async_dispatch: bool = False


class ServingEngine:
    """Drives an :class:`InferenceEngineV2` as a servable endpoint."""

    def __init__(self, engine, clock=None, config: ServingConfig = None, monitor=None,
                 tracer=None, metrics=None, trace_track: str = "serving",
                 recorder=None):
        self.engine = engine
        self.clock = clock if clock is not None else VirtualClock()
        self.config = config or ServingConfig()
        self.monitor = monitor
        # telemetry (docs/OBSERVABILITY.md): ``tracer`` collects one trace
        # per request (phase spans derived from the request's state history
        # at terminal time — the per-token hot path does NO tracer work);
        # ``metrics`` is a MetricsRegistry for always-on counters/histograms;
        # ``recorder`` is the fleet flight recorder (attached directly, not
        # through the tracer, so a recorder-without-tracer fleet still gets
        # the replica-side control events)
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.metrics = metrics
        self.recorder = recorder
        self.trace_track = trace_track
        # uid -> (trace_id, parent_span_id, clamp_start): parent_span_id is
        # the fleet router's attempt span when this frontend is a replica
        # (phases clamp to the dispatch time so resumed attempts don't
        # double-count the backdated client arrival); both None standalone
        self._trace_ctx: Dict[int, Tuple[int, Optional[int], Optional[float]]] = {}
        self.admission = AdmissionController(self.config.admission, engine)
        self.kvp = KVPressureManager(engine, youth_key=self._youth_key)
        self.stats = ServingStats()
        # host KV tier (serving/kvtier): set via attach_tier().  When
        # present, park()/resume() stage idle sessions host-side and
        # KV-pressure preemption demotes instead of plain-evicting.
        self.tier = None
        self._queue: List[ServingRequest] = []
        self._active: Dict[int, ServingRequest] = {}
        self._parked: Dict[int, ServingRequest] = {}
        self._requests: Dict[int, ServingRequest] = {}
        self._uids = itertools.count(max(engine.state.seqs.keys(), default=-1) + 1)
        self._events_step = 0
        self._t0 = self.clock.now()
        # step-anatomy fold cursors (telemetry/step_anatomy.py): compiles
        # already bridged into metrics/events, steps already mirrored into
        # the flight-recorder ring.  The compile cursor starts at the
        # recorder's CURRENT log length so pre-frontend warm-up compiles
        # (harnesses warm before building the frontend) are not re-counted
        # as serving-time recompiles.
        self._compiles_seen = len(getattr(engine, "anatomy",
                                          NULL_ANATOMY).compiles)
        self._anat_steps_seen = 0
        # EWMA of clock-seconds per tick-with-work (load_stats input for the
        # fleet router's least-loaded policy); None until the first step runs
        self._ewma_step_s: Optional[float] = None
        # async double-buffered dispatch (config.async_dispatch): the
        # step enqueued last tick, completed at the NEXT tick's readback —
        # (InFlightStep, charged_cost, dispatch_ts) or None
        self._inflight = None
        # a fleet ReplicaClockView over a shared VirtualClock quantizes
        # latencies exactly like a bare VirtualClock — unwrap it so the
        # warning below fires for fleet replicas too
        base_clock = getattr(self.clock, "shared", self.clock)
        if isinstance(base_clock, VirtualClock) and \
                engine.econfig.decode_steps_per_dispatch > 1:
            # the fused decode path delivers up to k tokens per tick while
            # the virtual clock advances one step_cost — TTFT/TPOT would be
            # per-DISPATCH quantities, understated up to k-fold
            logger.warning(
                f"ServingEngine on a VirtualClock with decode_steps_per_dispatch="
                f"{engine.econfig.decode_steps_per_dispatch}: per-token latency "
                "metrics are quantized to fused-dispatch granularity; build the "
                "engine with decode_steps_per_dispatch=1 for SLA measurement")
        # step planning follows request priority/arrival instead of
        # dict-iteration (put) order — see SplitFuseScheduler.order_key
        if engine.scheduler.order_key is not None:
            logger.warning("ServingEngine: replacing an existing scheduler order_key "
                           "(another frontend on this engine? call close() on it first)")
        engine.scheduler.order_key = self._seq_order_key

    # ---------------------------------------------------------------- keys

    def _priority_key(self, req: ServingRequest, now: float):
        cls = req.priority
        if self.config.aging_interval > 0:
            cls -= (now - req.arrival_ts) / self.config.aging_interval
        return (cls, req.arrival_ts, req.uid)

    def _seq_order_key(self, seq):
        req = self._requests.get(seq.uid)
        if req is None:  # non-serving sequence (direct engine.put user): first
            return (float("-inf"), -1.0, seq.uid)
        return self._priority_key(req, self.clock.now())

    def _youth_key(self, uid: int):
        """Preemption victim order: least-urgent class first, then youngest
        arrival (least sunk work, weakest FCFS claim).  Uses the SAME aged
        priority as admission — a request that aged into urgency and got
        admitted must not then be the perpetual eviction victim on its raw
        class (admit/preempt ping-pong would undo the anti-starvation)."""
        req = self._requests.get(uid)
        if req is None:
            return (float("-inf"), float("-inf"), uid)
        return self._priority_key(req, self.clock.now())

    # -------------------------------------------------------------- submit

    def submit(self, prompt: Sequence[int], max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None, arrival_ts: Optional[float] = None,
               priority: float = 0.0, stream: Optional[Callable] = None,
               retry_policy=None, resume_tokens: Optional[Sequence[int]] = None,
               trace_id: Optional[int] = None,
               parent_span_id: Optional[int] = None,
               spec: Optional[bool] = None,
               kv_snapshot=None) -> ServingRequest:
        """Enqueue one request.  NEVER raises on overload: the returned
        request's state is REJECTED (with ``reject_reason``) when admission
        refuses it — callers inspect, the serving loop keeps running.

        ``resume_tokens``: tokens this request already generated on ANOTHER
        engine (fleet failover: its previous replica died mid-decode).  They
        seed ``req.tokens`` so admission prefills ``prompt + resume_tokens``
        and greedy decode continues with the identical next token — the same
        recompute-on-resume contract KV-pressure preemption uses, across
        replicas.  ``max_new_tokens`` still bounds the TOTAL output (resumed
        tokens included); it must exceed ``len(resume_tokens)``.

        ``trace_id`` / ``parent_span_id``: trace propagation (telemetry).
        A fleet router passes its client trace id plus the per-replica
        attempt span so this request's phase spans land in the CLIENT's
        trace; standalone, a fresh trace id is allocated per request.

        ``spec``: per-request speculative-decoding control — ``False``
        opts this request out of an engine-level ``SpecConfig`` (it rides
        verify rounds as a plain 1-token row), ``True``/``None`` keep the
        engine default.  On a spec-less engine the flag is a no-op.
        Acceptance lands on ``req.spec_proposed/spec_accepted`` and the
        ``spec/*`` metrics as the request decodes.

        ``kv_snapshot`` (a ``kvtransfer.KVSnapshot``): host-staged KV for
        ``prompt + resume_tokens``, exported from another replica.  At
        admission the engine tries the KV-IMPORT FAST PATH — scatter the
        staged pages into its arena and continue decode without
        recomputing the prompt; any rejection (crc mismatch, geometry
        drift, no page room) falls back to the ordinary
        recompute-on-resume prefill automatically, with the fallback
        counted on ``stats.kv_import_fallbacks`` and the
        ``migration/import_fallback`` metric.  Either way the snapshot is
        consumed at first admission (a preemption AFTER import resumes by
        recompute, as always).

        ``retry_policy`` (a resilience ``RetryPolicy``): re-probe admission
        while the rejection is TRANSIENT (``queue_full`` — pressure that
        drains); structural rejections (infeasible request) are final
        immediately.  The FIRST wait honors the admission controller's
        ``retry_after`` hint (queue depth x EWMA step seconds — when
        capacity plausibly exists) instead of a blind exponential ladder;
        only if that informed probe still finds the queue full does the
        policy's backoff schedule run, within its attempt/time budget.
        Each wait runs ``tick()``\\ s so the loop makes real progress while
        the submitter waits (in a single-threaded clock-driven driver
        nothing else would drain the queue); deadlines that expire during
        the wait expire because time — and engine work — genuinely
        passed.  A request rejected with ``queue_full`` carries the hint
        on ``req.retry_after`` either way."""
        from ..resilience import fault_injection as _fi
        _fi.check("serving.admit")  # chaos site: admission stragglers/faults
        now = self.clock.now() if arrival_ts is None else float(arrival_ts)
        if max_new_tokens is None:
            max_new_tokens = self.engine.econfig.max_new_tokens
        if max_new_tokens <= 0:
            raise ValueError(f"max_new_tokens must be positive, got {max_new_tokens}")
        uid = next(self._uids)
        while uid in self.engine.state.seqs:
            # a direct engine.put() caller (mixed use) claimed this uid after
            # the counter was snapshotted — skip past, never alias their
            # sequence (get_or_create would EXTEND its token list)
            uid = next(self._uids)
        req = ServingRequest(
            uid=uid, prompt=list(prompt), arrival_ts=now,
            max_new_tokens=max_new_tokens,
            deadline=deadline, priority=priority, stream=stream, spec=spec)
        if resume_tokens:
            if len(resume_tokens) >= max_new_tokens:
                raise ValueError(
                    f"resume_tokens ({len(resume_tokens)}) must leave output budget "
                    f"under max_new_tokens ({max_new_tokens}) — a fully-generated "
                    "request has nothing to resume")
            req.tokens.extend(int(t) for t in resume_tokens)
        req.kv_snapshot = kv_snapshot
        self._requests[req.uid] = req
        self.stats.submitted += 1
        if self.tracer.enabled:
            # fleet mode (parent attempt span given): phases clamp to the
            # submission instant so a resumed attempt's backdated arrival
            # doesn't double-count the previous attempt's time
            self._trace_ctx[req.uid] = (
                trace_id if trace_id is not None else self.tracer.new_trace_id(),
                parent_span_id,
                self.clock.now() if parent_span_id is not None else None)
        if self.metrics is not None:
            self.metrics.counter("serving/submitted").inc()
        ok, reason = self.admission.submit_ok(req, len(self._queue))
        if not ok and reason == "queue_full" and retry_policy is not None:
            from ..resilience.retry import backoff_until

            # FIRST honor the admission controller's retry-after hint: one
            # informed wait sized to the queue's estimated drain time,
            # ticking so the queue actually drains.  Only if the hinted
            # wait was not enough does the blind exponential ladder run —
            # the hint turns most backoffs into a single well-aimed probe.
            # The hint is CLAMPED to the policy's time budget (the caller
            # bounded how long submit may block — the hinted wait and the
            # ladder share ONE budget, not a budget each) and to the
            # request's own deadline (waiting past it can only time out).
            hint = self.admission.retry_after_hint(
                len(self._queue), self._ewma_step_s)
            hint = min(hint, retry_policy.budget_s)
            if deadline is not None:
                hint = max(0.0, min(hint, deadline - self.clock.now()))
            t_hint = self.clock.now()
            target = t_hint + hint
            ok, why = False, "queue_full"   # a zero hint changes nothing
            while self.clock.now() < target:
                before = self._progress_marker()
                self.tick()
                ok, why = self.admission.submit_ok(req, len(self._queue))
                if ok or why != "queue_full":
                    break   # capacity freed early (or drained into a
                    # structural answer): don't sit out the rest of the hint
                if self._progress_marker() == before:
                    # nothing admissible moved: wait out the remainder of
                    # the hint instead of spinning (WallClock sleeps here;
                    # a productive tick is progress, not a spin, so the
                    # marker — never the raw clock — decides; the wait
                    # itself cannot change what submit_ok reads)
                    self.clock.wait_until(target)
                    self._note_idle()
            if ok:
                reason = None
            elif why != "queue_full":
                reason = why   # drained into a structural rejection
            else:
                def _probe():
                    self.tick()  # drain queued work: backoff must be able to succeed
                    got, w = self.admission.submit_ok(req, len(self._queue))
                    return got, w == "queue_full"

                ladder = dataclasses.replace(
                    retry_policy, budget_s=max(
                        0.0, retry_policy.budget_s - (self.clock.now() - t_hint)))
                if backoff_until(_probe, ladder, self.clock,
                                 site="serving.admit"):
                    ok, reason = True, None
                else:
                    ok, reason = self.admission.submit_ok(req, len(self._queue))
            # the clock advanced (and the engine ticked) during the
            # backoff — a terminal transition stamped with the stale
            # pre-backoff `now` would erase the wait the request lived
            now = self.clock.now()
        if not ok:
            req.reject_reason = reason
            if reason == "queue_full":
                # transient: tell the client WHEN to come back (the fleet
                # router and submit(retry_policy=) both honor this)
                req.retry_after = self.admission.retry_after_hint(
                    len(self._queue), self._ewma_step_s)
            req.to(RequestState.REJECTED, now)
            self.stats.record_reject(reason)
            self.stats.record_terminal(req)
            self._requests.pop(req.uid, None)
            if self.metrics is not None:
                self.metrics.counter("serving/rejected").inc()
            self._trace_terminal(req, now)
            self._emit([("serving/rejected", 1.0, self._next_event_step())])
            return req
        self._queue.append(req)
        return req

    # ---------------------------------------------------------------- tick

    def tick(self) -> Dict[int, List[int]]:
        """One serving iteration.  Serial mode (default): expire
        deadlines, admit, resolve KV pressure, run one engine step,
        deliver tokens.  Async mode (``config.async_dispatch``): complete
        the step dispatched LAST tick, then enqueue the next one — see
        :meth:`_tick_pipelined`.  Returns the completed step's
        {uid: [tokens]} (empty when nothing was runnable)."""
        if self.tier is not None:
            # capacity-pressure demotion (docs/SERVING.md "Tiered KV"):
            # coldest-first device→host demotion / host drops once the
            # configured occupancy watermarks are crossed — a no-op with
            # the default (None) watermarks
            self.tier.enforce_watermarks()
        if self.config.async_dispatch:
            return self._tick_pipelined()
        return self._tick_serial()

    def _tick_serial(self) -> Dict[int, List[int]]:
        """The strictly serial host→device step loop.

        With a step-anatomy recorder on the engine, the tick opens the
        step window BEFORE the admission/preflight work (``step_begin``
        is idempotent — the engine's own call then no-ops) and attributes
        planning up to the engine call as the ``schedule`` segment; on
        clock-charged steps (VirtualClock / fleet clock views) the
        charged cost is forwarded as the step's device seconds.  Ticks
        that run no step leave the window open — their host work folds
        into the step that eventually runs, which is exactly the loop tax
        the anatomy exists to expose."""
        anat = getattr(self.engine, "anatomy", NULL_ANATOMY)
        if anat.enabled:
            anat.step_begin()
        now = self.clock.now()
        self._expire(now)
        self._admit(now)
        if not self._active:
            return {}
        evicted, plan = self.kvp.resolve()
        for seq in evicted:
            self._on_preempted(seq, now)
        if not self._active:  # everything runnable got preempted/expired
            return {}
        if not plan.decode and not plan.prefill:
            # every active sequence is paused (mid-KV-migration): there is
            # no step to run and no cost to charge — the export chunks are
            # the fleet driver's work, not this replica's step loop's
            return {}
        if anat.enabled:
            anat.mark("schedule")
        cost = 1.0
        if self.config.step_cost is not None:
            cost = self.config.step_cost(plan.planned_tokens)
        t_step = self.clock.now()
        out = self.engine.step(plan)
        # clock-domain step seconds: clocks that account the cost themselves
        # (VirtualClock, ReplicaClockView) return it; WallClock returns None
        # and the real elapsed time is measured
        charged = self.clock.on_step(cost)
        dt = charged if charged is not None else self.clock.now() - t_step
        self._ewma_step_s = dt if self._ewma_step_s is None \
            else 0.8 * self._ewma_step_s + 0.2 * dt
        if anat.enabled:
            if charged is not None:
                anat.charge_last_step(charged)
            self._fold_anatomy(anat)
        # fold BEFORE _deliver: finishing a request flushes its engine
        # sequence, which pops its last_spec_round entry
        self._record_spec_rounds()
        self._deliver(out, self.clock.now())
        return out

    def _tick_pipelined(self) -> Dict[int, List[int]]:
        """Async double-buffered serving tick: step g+1's host-side work
        runs while step g executes on device, blocking only at the
        sample/accept readback.

        Pipeline stages, in tick order:

        1. **overlap window** — deadline expiry and admission run while
           last tick's dispatch is still in flight; with a recorder
           attached the stretch lands in the open step's ``overlap``
           segment (loop tax hidden under device time).  A sequence
           flushed here while in flight is skipped whole at the fold
           (object-identity guards in ``complete_step``) — its computed
           tokens are discarded, never half-applied.
        2. **complete** — the one blocking point: read back step g's
           tokens and fold them into engine state.
        3. **dispatch** — KV-pressure preflight, plan, and enqueue step
           g+1.  The clock cost is charged AT DISPATCH (not completion),
           so every ``clock.now()`` reading a request observes matches
           the serial loop's.
        4. **deliver** — step g's tokens reach their requests while step
           g+1 is already on device; the timestamp is captured BEFORE
           g+1's charge, so delivery/finish times equal the serial
           loop's (sum of costs through step g).  Runs in a ``finally``:
           a g+1 dispatch failure must never lose g's delivered tokens.
        """
        anat = getattr(self.engine, "anatomy", NULL_ANATOMY)
        now = self.clock.now()
        self._expire(now)
        self._admit(now)
        if anat.enabled:
            anat.mark("overlap")   # no-op when no step window is open
        out: Dict[int, List[int]] = {}
        if self._inflight is not None:
            inf, charged, t_dispatch = self._inflight
            self._inflight = None
            out = self.engine.complete_step(inf)
            dt = charged if charged is not None \
                else self.clock.now() - t_dispatch
            self._ewma_step_s = dt if self._ewma_step_s is None \
                else 0.8 * self._ewma_step_s + 0.2 * dt
            if anat.enabled:
                self._fold_anatomy(anat)
            # fold BEFORE the next dispatch (it clears last_spec_round)
            # and BEFORE _deliver (finishing a request flushes its engine
            # sequence, which pops its entry)
            self._record_spec_rounds()
        # serial-parity delivery timestamp: the clock already carries
        # every step cost through g (charged at its own dispatch), and
        # g+1's charge has not landed yet
        t_deliver = self.clock.now()
        if not self._active:
            self._deliver(out, t_deliver)
            return out
        if anat.enabled:
            anat.step_begin()      # open step g+1's window for its planning
        try:
            evicted, plan = self.kvp.resolve()
            for seq in evicted:
                self._on_preempted(seq, now)
            if self._active and (plan.decode or plan.prefill):
                if anat.enabled:
                    anat.mark("schedule")
                cost = 1.0
                if self.config.step_cost is not None:
                    cost = self.config.step_cost(plan.planned_tokens)
                t_dispatch = self.clock.now()
                inf = self.engine.dispatch_step(plan)
                if inf is not None:
                    # charge-at-dispatch: clock-accounted costs land when
                    # the step enqueues, keeping arrivals/admission and
                    # delivery timestamps aligned with the serial loop
                    charged = self.clock.on_step(cost)
                    if charged is not None and anat.enabled:
                        # the virtual charge is this step's device time —
                        # claim it now so the next overlap window cannot
                        # absorb it as host work
                        anat.device_mark()
                    self._inflight = (inf, charged, t_dispatch)
        finally:
            self._deliver(out, t_deliver)
        return out

    def _fold_anatomy(self, anat) -> None:
        """Bridge the engine's step-anatomy state into the serving
        telemetry surfaces: new JIT cache misses become ``engine/
        recompiles`` counter increments (steady-state ones additionally
        the ``engine/recompile_steady_state`` counter + event — the AOT
        regression signal, loud by design), and the just-closed step is
        mirrored as one bounded ``anatomy/step`` span on this frontend's
        flight-recorder track."""
        compiles = anat.compiles
        if len(compiles) > self._compiles_seen:
            for c in list(compiles)[self._compiles_seen:]:
                if self.metrics is not None:
                    self.metrics.counter("engine/recompiles").inc()
                if c.steady:
                    if self.metrics is not None:
                        self.metrics.counter(
                            "engine/recompile_steady_state").inc()
                    logger.warning(
                        f"steady-state recompile: program {c.key} compiled "
                        f"at step {c.step_index} AFTER the warm-up boundary "
                        "— the bucketed step set is not closed")
                    self._emit([("engine/recompile_steady_state", 1.0,
                                 self._next_event_step())])
            self._compiles_seen = len(compiles)
        if anat.total_steps > self._anat_steps_seen:
            unseen = anat.total_steps - self._anat_steps_seen
            self._anat_steps_seen = anat.total_steps
            recorder = self.recorder if self.recorder is not None \
                else getattr(self.tracer, "recorder", None)
            if recorder is not None:
                # mirror EVERY unseen closed step, not just the newest —
                # a chaos-failed step closes its record but skips that
                # tick's fold, and its anatomy is exactly what a
                # crash-scoped dump needs (deque eviction bounds the tail)
                steps = anat.steps
                for rec in list(steps)[-min(unseen, len(steps)):]:
                    recorder.span(
                        "anatomy/step", f"anatomy/{self.trace_track}",
                        rec.end_ts - rec.wall_s, rec.end_ts,
                        attrs={"shape": rec.shape_key,
                               "host_gap_s": round(rec.host_gap_s, 9),
                               "host_s": round(rec.host_s(), 9),
                               "device_s": round(rec.device_s, 9),
                               "compiles": rec.compiles})

    def export_kv_gauges(self) -> None:
        """Publish the engine's KV-arena occupancy onto the metrics
        registry (``kv/*`` gauges — page occupancy, free-run
        fragmentation, prefix-cache share; docs/OBSERVABILITY.md "Step
        anatomy").  Standalone frontends call this at whatever cadence
        they report; the fleet router exports the per-replica variants
        once per fleet round instead.  No-op without a registry."""
        if self.metrics is None:
            return
        st = self.engine.kv.arena_stats()
        m = self.metrics
        m.gauge("kv/pages_in_use").set(st["in_use"])
        m.gauge("kv/pages_free").set(st["free"])
        m.gauge("kv/page_occupancy").set(st["occupancy"])
        m.gauge("kv/free_run_fragmentation").set(st["free_run_fragmentation"])
        m.gauge("kv/prefix_cache_pages").set(st["prefix_cache_pages"])
        m.gauge("kv/prefix_cache_share").set(st["prefix_cache_share"])
        if self.tier is not None:
            m.gauge("kv/host_pages").set(self.tier.host.pages_used)
            frac = self.tier.hidden_frac
            m.gauge("kv/tier_prefetch_hidden_frac").set(
                frac if frac is not None else 0.0)

    def _record_spec_rounds(self) -> None:
        """Fold the step's verify-round accounting (``engine.last_spec_round``,
        one ``(proposed, accepted, rollback_pages)`` per speculating uid)
        into per-request counters and the ``spec/*`` metrics."""
        rounds = getattr(self.engine, "last_spec_round", None)
        if not rounds:
            return
        for uid, (proposed, accepted, rb_pages) in rounds.items():
            req = self._active.get(uid)
            if req is not None:
                req.spec_proposed += proposed
                req.spec_accepted += accepted
                req.spec_rollback_pages += rb_pages
            if self.metrics is not None and proposed:
                self.metrics.counter("spec/proposed").inc(proposed)
                self.metrics.counter("spec/accepted").inc(accepted)
                self.metrics.counter("spec/rollback_pages").inc(rb_pages)
                self.metrics.histogram("spec/acceptance_rate").record(
                    accepted / proposed)

    def _expire(self, now: float) -> None:
        if not self.config.kill_on_deadline:
            return
        for req in [r for r in self._queue if r.deadline is not None and now > r.deadline]:
            self._queue.remove(req)
            self._finish(req, RequestState.TIMED_OUT, now)
        for uid in [u for u, r in self._active.items()
                    if r.deadline is not None and now > r.deadline]:
            req = self._active.pop(uid)
            self.engine.flush(uid)  # reclaim KV pages + engine state
            self._finish(req, RequestState.TIMED_OUT, now)
        for uid in [u for u, r in self._parked.items()
                    if r.deadline is not None and now > r.deadline]:
            req = self._parked.pop(uid)
            if self.tier is not None:
                self.tier.discard(uid)  # reclaim host pages + prefetch slot
            self._finish(req, RequestState.TIMED_OUT, now)

    def _admit(self, now: float) -> None:
        """FCFS-with-aging head-of-line admission: the queue is served in
        priority order and stops at the first request that does not fit —
        skipping ahead would starve large requests behind a stream of small
        ones (the aging mechanism exists to prevent exactly that)."""
        self._queue.sort(key=lambda r: self._priority_key(r, now))
        reserved = 0  # pages promised to this tick's earlier admissions
        while self._queue:
            req = self._queue[0]
            if not self.admission.can_start(req, reserved_pages=reserved):
                break
            self._queue.pop(0)
            assert req.remaining_new_tokens > 0, req
            assert req.uid not in self.engine.state.seqs, (
                f"uid {req.uid} already live in the engine (direct put() "
                "collision) — cannot admit")
            imported = req.kv_snapshot is not None and self._try_import(req)
            if not imported:
                if self.tier is not None:
                    # warm-on-host prefix promotion: pull any host-staged
                    # chain tail for this prompt device-side first, so the
                    # prefill below skips it via the ordinary match()
                    self._promote_prefix_for(req)
                self.engine.put([req.uid], [req.engine_tokens()],
                                max_new_tokens=req.remaining_new_tokens)
            if req.spec is not None:
                # re-applied on every (re)admission: preemption/flush
                # cleared the engine's per-uid opt-out
                self.engine.set_spec(req.uid, req.spec)
            # a tier promotion may have stalled admission (the non-hidden
            # transfer remainder advanced the clock): stamp with the
            # settled time, never a pre-stall reading
            adm_now = max(now, self.clock.now())
            if req.admitted_ts is None:
                req.admitted_ts = adm_now
            req.to(RequestState.PREFILL, adm_now)
            self._active[req.uid] = req
            reserved += self.admission._start_pages(req)

    def _try_import(self, req: ServingRequest) -> bool:
        """KV-import fast path at admission: scatter ``req.kv_snapshot``
        into this engine's arena so decode continues without recomputing
        the prompt.  Returns False — after consuming the snapshot — on any
        ordinary rejection (torn snapshot, geometry/dtype drift, token
        mismatch, no page room): the caller falls back to the recompute
        prefill, which is always correct.  Replica-fatal failures
        (``InjectedCrash`` driver death, ``DeviceLossError``) re-raise with
        the request pushed back onto the queue so the kill path collects
        it for failover."""
        from ..resilience.fault_injection import DeviceLossError
        from .kvtier import HostKVHandle
        from .kvtransfer import import_snapshot
        snap, req.kv_snapshot = req.kv_snapshot, None   # consumed either way
        if isinstance(snap, HostKVHandle):
            # parked/demoted locally: resolve the handle through the tier
            # (kv.promote chaos site, prefetch-window settlement).  A None
            # snapshot is any degradable miss — recompute owns the resume.
            snap, stall, window = self.tier.claim(
                req.uid, req.engine_tokens(), self.clock.now())
            if snap is None:
                self.stats.kv_import_fallbacks += 1
                if self.metrics is not None:
                    self.metrics.counter("migration/import_fallback").inc()
                return False
            self._charge_promote_stall(req, stall, window)
        try:
            import_snapshot(self.engine, req.uid, req.engine_tokens(), snap,
                            max_new_tokens=req.remaining_new_tokens)
        except InjectedCrash:
            raise  # simulated DRIVER death; chaos tests must see it
        except DeviceLossError:
            # this replica's device is gone: re-queue the request so the
            # health-driven kill path collects it for failover, then let
            # the loss classify this replica dead.  The snapshot is HOST
            # memory — it survives this device and goes back on the
            # request so failover can retry the import on a survivor.
            req.kv_snapshot = snap
            self._queue.insert(0, req)
            raise
        except Exception as e:
            logger.warning(f"kv import rejected for uid={req.uid} "
                           f"({e}); falling back to recompute-on-resume")
            self.stats.kv_import_fallbacks += 1
            if self.metrics is not None:
                self.metrics.counter("migration/import_fallback").inc()
            return False
        self.stats.kv_imports += 1
        if self.metrics is not None:
            self.metrics.counter("migration/kv_imports").inc()
        return True

    def _charge_promote_stall(self, req: ServingRequest, stall: float,
                              window) -> None:
        """Account one settled promotion transfer: wait out the non-hidden
        remainder (the prefetched part already hid under earlier device
        windows) and record the transfer interval on the request so
        telemetry carves it out of the queued phase as ``phase/promote``."""
        if stall > 0:
            self.clock.wait_until(self.clock.now() + stall)
            anat = getattr(self.engine, "anatomy", NULL_ANATOMY)
            if anat.enabled:
                anat.mark("promote_wait")
        if window is not None:
            req.promote_windows.append(window)

    def _promote_prefix_for(self, req: ServingRequest) -> None:
        """Pre-admission warm-on-host promotion: if the host tier holds a
        chain tail for this request's tokens beyond what the device prefix
        cache has, scatter it back and adopt it so the prefill's
        ``match()`` attaches those pages instead of recomputing their KV.
        Failures degrade silently to the ordinary cold prefill."""
        n, stall, window = self.tier.promote_prefix(
            req.engine_tokens(), self.clock.now())
        if n:
            self._charge_promote_stall(req, stall, window)

    def import_prefix(self, snapshot) -> int:
        """Adopt a host-staged hot-prefix snapshot into this replica's
        prefix cache (``kvtransfer.import_prefix``) so the NEXT admission
        of a matching prompt attaches the pages instead of recomputing
        their KV — the fleet prefix directory's cold-replica warm-up path
        (docs/SERVING.md "Prefix directory").  Returns pages imported;
        raises a ``SnapshotError`` subclass on rejection (the caller
        dispatches cold and counts the fallback).  Unlike the migration
        import this touches no request state — it is pure cache
        population, safe before the request is even submitted here."""
        from .kvtransfer import import_prefix
        n = import_prefix(self.engine, snapshot)
        if n:   # already-warm no-ops are not imports
            self.stats.prefix_imports += 1
            self.stats.prefix_import_pages += n
            if self.metrics is not None:
                self.metrics.counter("prefix/import").inc()
        return n

    # ------------------------------------------------- tiered KV (kvtier)

    def attach_tier(self, tier) -> None:
        """Wire a ``kvtier.TieredKVManager`` into this frontend: park()/
        resume() become available, KV-pressure preemption demotes victims
        to the host tier before releasing their pages (demotion-first),
        and admission resolves ``HostKVHandle`` snapshots through the
        tier's prefetch-hidden promotion path (docs/SERVING.md "Tiered
        KV")."""
        self.tier = tier
        self.kvp.tier = tier
        if tier.metrics is None:
            tier.metrics = self.metrics

    def park(self, uid: int, phase: str = "parked") -> bool:
        """Park an idle decoding session: demote its KV pages to the host
        tier, release its engine sequence, and hold the request in PARKED
        until :meth:`resume`.  The session costs ZERO device pages while
        parked; its resume promotes the staged pages back (prefetched, so
        the h2d transfer hides under intervening steps) instead of
        recomputing the prompt.  Returns False when the request is not an
        active unfinished DECODE (parking mid-prefill or mid-step work is
        not a supported window) or has no tier to park into.  A failed
        demotion still parks — that resume just recomputes (the
        kv_snapshot stays None), the ladder's never-wrong fallback.

        ``phase`` labels the PARKED interval for telemetry ("parked" for
        idle-session parks, "tool_stall" for a session's mid-generation
        tool-call stall — serving/sessions); the park/resume machinery is
        identical either way."""
        req = self._active.get(uid)
        if self.tier is None or req is None \
                or req.state is not RequestState.DECODE:
            return False
        seq = self.engine.state.seqs.get(uid)
        if seq is None or seq.done or seq.paused:
            return False
        now = self.clock.now()
        # demote BEFORE preempt: the gather needs the pages still live
        handle = self.tier.demote_sequence(uid)
        self.engine.preempt(uid)
        del self._active[uid]
        req.park_phase = phase
        req.to(RequestState.PARKED, now)
        req.kv_snapshot = handle
        self._parked[uid] = req
        self.stats.parks += 1
        if self.metrics is not None:
            self.metrics.counter("kv/park").inc()
        self._emit([("kv/park", 1.0, self._next_event_step())])
        return True

    def prefetch_resume(self, uid: int) -> bool:
        """Hint that a PARKED request will resume soon: issue its h2d
        promotion transfer NOW, so it runs under the device windows of the
        steps between this call and the actual :meth:`resume` — the
        prefetch-hidden promotion contract.  A session controller that
        knows the next user turn is coming (typing indicator, scheduled
        agent step) calls this ahead of resume; an unhinted resume still
        prefetches, it just has less time to hide.  Idempotent; False for
        an unknown/non-parked/snapshot-less uid."""
        req = self._parked.get(uid)
        if req is None or req.kv_snapshot is None or self.tier is None:
            return False
        self.tier.prefetch(uid, req.kv_snapshot.n_pages, self.clock.now())
        return True

    def resume(self, uid: int) -> bool:
        """Re-enqueue a PARKED request and issue its promotion prefetch
        (if :meth:`prefetch_resume` didn't already), so by the time
        admission reaches it the h2d transfer has (partly or wholly)
        hidden under the steps in between.  Returns False for an
        unknown/non-parked uid."""
        req = self._parked.pop(uid, None)
        if req is None:
            return False
        now = self.clock.now()
        req.to(RequestState.QUEUED, now)
        if req.kv_snapshot is not None and self.tier is not None:
            self.tier.prefetch(uid, req.kv_snapshot.n_pages, now)
        self._queue.append(req)
        self.stats.resumes += 1
        if self.metrics is not None:
            self.metrics.counter("kv/resume").inc()
        self._emit([("kv/resume", 1.0, self._next_event_step())])
        return True

    # ----------------------------------------------------------- migration

    def begin_migration(self, uid: int, chunk_pages: int = 4, source=None):
        """Pause a request for KV export (docs/SERVING.md "Disaggregated
        serving").  Its engine sequence keeps its pages but leaves step
        planning, so the pages stay byte-stable while the returned
        ``kvtransfer.KVExporter`` stages them chunk by chunk between this
        replica's ongoing ticks.

        Two migratable windows:

        * LATE PREFILL — the DistServe handoff boundary: at least one full
          page of prompt KV is staged and at most one prefill chunk
          remains, so the decode replica runs only the final chunk (which
          samples the first token) and the staging pause lands in TTFT,
          never in the token cadence;
        * DECODE — the catch-up path (short prompts prefill whole in one
          chunk and are first observable here; failed earlier migrations
          retry here).

        Returns None when the request is in neither window (not active,
        already paused, finished, or too early in prefill) or the engine's
        cache layout is not exportable — the router just skips it."""
        from .kvtransfer import KVExporter, KVImportError
        req = self._active.get(uid)
        if req is None or req.state not in (RequestState.PREFILL,
                                            RequestState.DECODE):
            return None
        seq = self.engine.state.seqs.get(uid)
        if seq is None or seq.done or seq.paused:
            return None
        if req.state is RequestState.PREFILL:
            if seq.seen_tokens < self.engine.kv.page_size or \
                    seq.remaining_prefill > self.engine.scheduler.config.prefill_chunk:
                return None  # too early: let the prefill replica keep grinding
        elif not seq.in_decode:
            return None
        seq.paused = True
        try:
            exporter = KVExporter(self.engine, uid, chunk_pages=chunk_pages,
                                  source=source)
        except KVImportError as e:
            # structurally unexportable on THIS engine (e.g. the
            # unroll_layers per-layer tuple cache layout): not a migratable
            # request, not an error — the caller keeps serving it here
            seq.paused = False
            logger.debug(f"begin_migration({uid}): not exportable ({e})")
            return None
        except Exception:
            seq.paused = False
            raise
        req.to(RequestState.MIGRATING, self.clock.now())
        return exporter

    def abort_migration(self, uid: int) -> None:
        """Resume a MIGRATING request in place (export failed, or no decode
        replica can take the handoff): the sequence re-enters step planning
        and the phase the pause interrupted (prefill or decode) continues
        on THIS replica exactly where it stopped."""
        req = self._active.get(uid)
        if req is None or req.state is not RequestState.MIGRATING:
            return
        seq = self.engine.state.seqs.get(uid)
        if seq is not None:
            seq.paused = False
        back = RequestState.DECODE if seq is not None and seq.in_decode \
            else RequestState.PREFILL
        req.to(back, self.clock.now())

    def complete_migration(self, uid: int) -> ServingRequest:
        """Close out a MIGRATING request whose snapshot fully exported: the
        engine sequence is flushed (pages released — full pages published
        to the prefix cache survive via the cache's refcount), the request
        reaches the MIGRATED terminal state on THIS replica, and the
        caller re-submits it on the decode replica with the snapshot.
        Returns the closed request."""
        now = self.clock.now()
        req = self._active.pop(uid)
        assert req.state is RequestState.MIGRATING, req
        self.engine.flush(uid)
        req.to(RequestState.MIGRATED, now)
        self.stats.record_terminal(req)
        self._requests.pop(req.uid, None)
        if self.metrics is not None:
            self.metrics.counter("serving/migrated").inc()
        self._trace_terminal(req, now)
        self._emit([("serving/migrated", 1.0, self._next_event_step())])
        return req

    def _on_preempted(self, seq, now: float) -> None:
        req = self._active.pop(seq.uid, None)
        if req is None:
            # a sequence put() directly on the engine by some other caller
            # (mixed use is allowed — _seq_order_key/_youth_key rank such
            # sequences so they are preempted only as a last resort).  Its
            # pages are already released; there is no request to requeue —
            # warn so the owner knows their sequence is gone
            logger.warning(f"KV pressure evicted non-frontend sequence uid={seq.uid} "
                           f"({len(seq.generated)} generated tokens lost to this "
                           "serving loop; re-put() it to resume)")
            self.stats.preemptions += 1
            return
        # every token the evicted sequence generated was already delivered to
        # req.tokens at the tick it was sampled — the descriptor can be
        # dropped without losing output
        req.to(RequestState.EVICTED, now)
        req.preemptions += 1
        self.stats.preemptions += 1
        if self.metrics is not None:
            self.metrics.counter("serving/preemptions").inc()
        self._emit([("serving/preempted", 1.0, self._next_event_step())])
        req.to(RequestState.QUEUED, now)
        if self.tier is not None and req.kv_snapshot is None:
            # demotion-first preemption (kv_pressure): the tier staged the
            # victim's pages before preempt freed them — ride the handle on
            # the request and start the promote prefetch NOW, so by
            # re-admission the h2d transfer has hidden under the steps that
            # ran in between
            handle = self.tier.handle_for(req.uid)
            if handle is not None:
                req.kv_snapshot = handle
                self.tier.prefetch(req.uid, handle.n_pages, now)
        self._queue.append(req)

    def _deliver(self, out: Dict[int, List[int]], now: float) -> None:
        for uid in sorted(out):
            toks = out[uid]
            req = self._active.get(uid)
            if req is None or not toks:
                continue
            if req.first_token_ts is None:
                req.first_token_ts = now
            if req.state is RequestState.PREFILL:
                req.to(RequestState.DECODE, now)
            req.tokens.extend(int(t) for t in toks)
            if req.stream is not None:
                try:
                    req.stream(req, [int(t) for t in toks], now)
                except InjectedCrash:
                    raise  # simulated process death; chaos tests must see it
                except Exception as e:
                    # one client's broken delivery sink (closed socket, ...)
                    # must not take down every other in-flight request; the
                    # request itself keeps generating — same stance as _emit
                    logger.warning(f"stream callback failed for uid={uid}: {e}")
                    req.stream = None
            seq = self.engine.state.seqs.get(uid)
            if seq is not None and seq.done:
                req.finish_ts = now
                self.engine.flush(uid)
                del self._active[uid]
                self._finish(req, RequestState.DONE, now)

    def _finish(self, req: ServingRequest, state: RequestState, now: float) -> None:
        req.to(state, now)
        self.stats.record_terminal(req)
        # terminal requests leave the lookup table (their engine sequence is
        # gone; keys here must not grow without bound in a long-lived
        # server) — the caller's handle and stats.finished keep the record
        self._requests.pop(req.uid, None)
        self._record_terminal_metrics(req, state, now)
        self._trace_terminal(req, now)
        step = self._next_event_step()
        events = [("serving/e2e_latency", now - req.arrival_ts, step),
                  ("serving/preemptions", float(req.preemptions), step)]
        if state is RequestState.DONE:
            if req.ttft is not None:
                events.append(("serving/ttft", req.ttft, step))
            if req.tpot is not None:
                events.append(("serving/tpot", req.tpot, step))
            if req.queue_wait is not None:
                events.append(("serving/queue_wait", req.queue_wait, step))
            events.append(("serving/deadline_met", 1.0 if req.met_deadline else 0.0, step))
        else:
            events.append(("serving/timed_out", 1.0, step))
        self._emit(events)

    # ----------------------------------------------------------- telemetry

    def _record_terminal_metrics(self, req: ServingRequest, state: RequestState,
                                 now: float) -> None:
        if self.metrics is None:
            return
        self.metrics.counter(f"serving/{state.value}").inc()
        self.metrics.histogram("serving/e2e_s").record(now - req.arrival_ts)
        if state is RequestState.DONE:
            if req.ttft is not None:
                self.metrics.histogram("serving/ttft_s").record(req.ttft)
            if req.tpot is not None:
                self.metrics.histogram("serving/tpot_s").record(req.tpot)
            if req.queue_wait is not None:
                self.metrics.histogram("serving/queue_wait_s").record(req.queue_wait)

    def _trace_terminal(self, req: ServingRequest, now: float) -> None:
        """Fold the finished request's state history into trace spans.

        Standalone: a ``request`` root span [arrival, terminal] on this
        frontend's track, with phase children (queued/prefill/decode) and
        one ``preempted`` span event per eviction.  Under a fleet router
        (an attempt parent span was passed at submit): only the phase
        children are emitted here — the router owns the root and the
        attempt spans, and phases clamp to the dispatch instant."""
        ctx = self._trace_ctx.pop(req.uid, None)
        if ctx is None:
            return
        from ..telemetry.spans import emit_attempt_spans
        trace_id, parent_id, clamp = ctx
        if parent_id is not None:
            emit_attempt_spans(self.tracer, req, trace_id, parent_id,
                               self.trace_track, end_ts=now, clamp_start=clamp)
            return
        root_id = self.tracer.reserve_span_id()
        emit_attempt_spans(self.tracer, req, trace_id, root_id,
                           self.trace_track, end_ts=now)
        events = [("preempted", ts, None) for st, ts in req.history
                  if st is RequestState.EVICTED]
        self.tracer.add_span(
            "request", trace_id, req.arrival_ts, now, span_id=root_id,
            track=self.trace_track, events=events,
            attrs={"uid": req.uid, "state": req.state.value,
                   "prompt_len": len(req.prompt), "n_tokens": len(req.tokens),
                   "preemptions": req.preemptions,
                   "reject_reason": req.reject_reason,
                   "ttft": req.ttft, "tpot": req.tpot,
                   "queue_wait": req.queue_wait,
                   "e2e": now - req.arrival_ts,
                   "deadline_met": req.met_deadline})

    # ---------------------------------------------------------------- loop

    def drain(self, max_ticks: int = 1_000_000) -> None:
        """Run ticks until queue + active are empty."""
        self._loop(pending_arrival=lambda: None, max_ticks=max_ticks)

    def loop(self, feed=None, max_ticks: int = 1_000_000) -> None:
        """Generic stall-guarded driver for callers that generate load
        dynamically (e.g. closed-loop benchmarking): ``feed()`` runs at the
        top of every iteration, may submit new requests, and returns the
        next known FUTURE arrival timestamp (or None).  Terminates when
        feed() has nothing pending and queue + active are empty; raises on
        a stall instead of spinning."""
        self._loop(pending_arrival=feed or (lambda: None), max_ticks=max_ticks)

    def run(self, arrivals: List[dict], max_ticks: int = 1_000_000) -> List[ServingRequest]:
        """Open-loop driver: ``arrivals`` is a list of submit() kwarg dicts,
        each with an ``arrival_ts``; requests are submitted as the clock
        passes their arrival time, idle gaps are skipped (VirtualClock) or
        slept (WallClock).  Returns the request objects in arrival order."""
        pending = sorted(arrivals, key=lambda a: a["arrival_ts"])
        reqs: List[ServingRequest] = []
        i = 0

        def feed():
            nonlocal i
            while i < len(pending) and pending[i]["arrival_ts"] <= self.clock.now():
                reqs.append(self.submit(**pending[i]))
                i += 1
            return pending[i]["arrival_ts"] if i < len(pending) else None

        self._loop(pending_arrival=feed, max_ticks=max_ticks)
        return reqs

    def _loop(self, pending_arrival, max_ticks: int) -> None:
        for _ in range(max_ticks):
            next_arrival = pending_arrival()
            if not self._queue and not self._active and self._inflight is None:
                if next_arrival is None:
                    return
                self.clock.wait_until(next_arrival)
                self._note_idle()
                continue
            marker = self._progress_marker()
            self.tick()
            if self._progress_marker() == marker:
                # nothing moved: only the passage of time can help (a future
                # arrival, or a queued deadline expiring — the latter only
                # when expiry is actually enforced) — jump to it
                waits = [r.deadline for r in self._queue if r.deadline is not None] \
                    if self.config.kill_on_deadline else []
                if next_arrival is not None:
                    waits.append(next_arrival)
                if not waits:
                    raise RuntimeError(
                        f"serving loop stalled: {len(self._queue)} queued, "
                        f"{len(self._active)} active, no admissible work and no "
                        "future event to wait for")
                self.clock.wait_until(min(waits) + 1e-9)
                self._note_idle()
        raise RuntimeError(f"serving loop exceeded max_ticks={max_ticks}")

    def _note_idle(self) -> None:
        """The loop just idled to a future event: exclude the jump from
        the step anatomy (idle is absent load, not step-loop tax — the
        next step is flagged ``after_idle`` instead)."""
        anat = getattr(self.engine, "anatomy", NULL_ANATOMY)
        if anat.enabled:
            anat.note_idle()

    def _progress_marker(self):
        # the in-flight flag counts as progress: a pipelined tick that
        # only dispatches (or only drains) changes nothing else yet
        return (len(self.stats.finished), self.stats.preemptions,
                len(self._queue), len(self._active),
                sum(s.seen_tokens for s in self.engine.state.seqs.values()),
                sum(len(r.tokens) for r in self._active.values()),
                self._inflight is not None)

    def fence(self) -> Dict[str, int]:
        """Cancel EVERY in-flight request on this frontend — the fleet
        fencing edge (docs/SERVING.md "Control-plane transport").  A
        replica that outlived its lease (a partition, not a death) kept
        decoding work the router has already re-dispatched to survivors;
        when the partition heals, the router's FENCE lands here and that
        zombie work — queued, active, or paused mid-migration — is
        dropped: engine sequences flushed (pages released; prefix-cache
        published pages survive via their refcounts), requests abandoned
        WITHOUT a terminal transition, exactly as a ``pool.kill`` abandons
        them — the fleet-level record was already re-homed, and a second
        terminal here would be the double-serve fencing exists to prevent.
        Returns the cancel counts for the fence ack."""
        if self._inflight is not None:
            # async mode with a step in flight: block on its readback and
            # discard the fold output — fenced work is dropped WHOLE (the
            # flushes below release its sequences), never half-applied
            inf, _, _ = self._inflight
            self._inflight = None
            try:
                self.engine.complete_step(inf)
            except InjectedCrash:
                raise
            except Exception as e:
                logger.warning(f"serving: in-flight step failed during "
                               f"fence ({e}); dropping it")
        counts = {"queued": len(self._queue), "active": len(self._active),
                  "parked": len(self._parked)}
        for req in list(self._queue):
            self._requests.pop(req.uid, None)
            self._trace_ctx.pop(req.uid, None)
        self._queue.clear()
        for uid in sorted(self._active):
            if uid in self.engine.state.seqs:
                self.engine.flush(uid)
            self._requests.pop(uid, None)
            self._trace_ctx.pop(uid, None)
        self._active.clear()
        for uid in sorted(self._parked):
            # parked zombies hold HOST pages, not device pages — reclaim
            # them through the tier, same no-terminal abandonment
            if self.tier is not None:
                self.tier.discard(uid)
            self._requests.pop(uid, None)
            self._trace_ctx.pop(uid, None)
        self._parked.clear()
        recorder = self.recorder if self.recorder is not None \
            else getattr(self.tracer, "recorder", None)
        if recorder is not None:
            # the replica-side half of the fencing episode, on this
            # frontend's own control track — pairs with the router-side
            # lease interval flipping FENCING→ALIVE in the same dump
            recorder.instant("ctrl/fence", f"ctrl/{self.trace_track}",
                             self.clock.now(), attrs=dict(counts))
        if counts["queued"] or counts["active"]:
            logger.warning(f"serving: fenced {counts['queued']} queued + "
                           f"{counts['active']} active request(s)")
        return counts

    def drop_trace(self, uid: int) -> None:
        """Discard this frontend's trace context for ``uid`` WITHOUT
        emitting phase spans — the router calls it when it fences or
        re-homes an attempt it can no longer trust (lease expiry): the
        router folds the attempt's observed history into the client trace
        itself, so a zombie's eventual terminal emission here would
        double-tile the attempt window.  Telemetry-only: request and
        engine state are untouched (the fence/kill path owns those)."""
        self._trace_ctx.pop(uid, None)

    def close(self) -> None:
        """Detach from the engine: restore dict-insertion step ordering and
        release the scheduler's reference to this frontend (a long-lived
        engine must not keep a discarded frontend — and its per-request
        stats log — reachable through order_key)."""
        if self.engine.scheduler.order_key is self._seq_order_key:
            self.engine.scheduler.order_key = None

    # ------------------------------------------------------------- metrics

    def load_stats(self) -> dict:
        """Cheap point-in-time load snapshot — the fleet router's policy
        input (O(active) dict/list reads, no engine work, safe to call every
        dispatch):

          queue_depth        — requests QUEUED at this replica (not yet in
                               the engine)
          active             — requests live in the engine (PREFILL/DECODE)
          outstanding_tokens — decode tokens still owed by active requests
                               (sum of ``remaining_new_tokens``) — the
                               least-outstanding-tokens policy's key
          free_kv_pages      — ``BlockedAllocator.free_pages`` right now
          ewma_step_s        — EWMA (alpha=0.2) of clock-seconds per
                               tick-with-work; None before the first step
        """
        return {
            "queue_depth": len(self._queue),
            "active": len(self._active),
            "parked": len(self._parked),
            "outstanding_tokens": sum(r.remaining_new_tokens for r in self._active.values()),
            "free_kv_pages": self.engine.kv.allocator.free_pages,
            "ewma_step_s": self._ewma_step_s,
        }

    def rebase_epoch(self) -> None:
        """Re-stamp this frontend's epoch at the clock's current reading.
        Callers that ``reset()`` a shared clock after expensive setup
        (fleet pool construction + engine warmup) must rebase every
        frontend built before the reset, or ``summary()``'s elapsed goes
        negative against the pre-reset ``_t0``."""
        self._t0 = self.clock.now()

    def summary(self) -> dict:
        """Aggregate stats record over this frontend's lifetime (see
        ``ServingStats.summary`` for the field definitions).  For a cheap
        instantaneous *load* snapshot — queue depth, outstanding decode
        tokens, free KV pages, EWMA step seconds — use :meth:`load_stats`;
        the fleet router polls that every dispatch, while ``summary()`` is
        the end-of-run report.

        ``monitor_dropped_events`` surfaces the ``MonitorMaster`` drop
        counter (the ``max_events`` cap): under a fleet's event volume the
        monitor sheds load silently at its own surface, and a summary that
        hid the loss would let a truncated metric stream read as a
        complete one.  ``dropped_spans`` is the tracer's equivalent."""
        rec = self.stats.summary(elapsed=self.clock.now() - self._t0)
        rec["monitor_dropped_events"] = int(getattr(self.monitor, "dropped_events", 0) or 0)
        rec["dropped_spans"] = int(self.tracer.dropped_spans)
        return rec

    def _next_event_step(self) -> int:
        self._events_step += 1
        return self._events_step

    def _emit(self, events) -> None:
        if self.monitor is None or not getattr(self.monitor, "enabled", True):
            return
        try:
            self.monitor.write_events(events)
        except InjectedCrash:
            raise  # simulated process death; chaos tests must see it
        except Exception as e:  # monitoring must never take down serving
            logger.warning(f"serving monitor write failed: {e}")
