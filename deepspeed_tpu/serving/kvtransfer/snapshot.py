"""Host-staged KV migration: export a sequence's paged KV device→host,
carry it as a crc-tagged :class:`KVSnapshot`, and import it into another
engine's arena so decode resumes there with byte-identical outputs.

This is the serving-side application of PAPER.md's L6 host-staging
machinery (``swap_tensor`` / host-memory-kind shardings — the
ZeRO-Offload/Infinity mapping): instead of optimizer shards, the staged
payload is a request's KV pages, and the consumer is another replica of
the fleet (DistServe-style prefill/decode disaggregation, Splitwise-style
phase splitting — see docs/SERVING.md "Disaggregated serving").

Protocol pieces:

* :class:`KVSnapshot` — the host-side container: the sequence's full token
  history + seen boundary at export time, the arena's per-page geometry,
  and the staged page blocks in export order, each crc32-tagged.
  ``verify()`` re-checksums every chunk; a torn or bit-rotted snapshot is
  rejected at import (→ the caller's recompute fallback), never silently
  decoded into wrong KV.
* :class:`KVExporter` — incremental device→host export of one PAUSED
  sequence, ``chunk_pages`` pages per :meth:`step_chunk` call, so a fleet
  driver interleaves export chunks with the source replica's ongoing
  decode steps instead of stalling them behind one bulk d2h.  The source
  sequence must stay paused and intact between chunks; if it was preempted
  (pages released) mid-flight the exporter raises :class:`SnapshotAborted`
  and the caller falls back to the token path.
* :func:`import_snapshot` — allocate fresh pages on the target engine,
  scatter the staged blocks into its arena, and materialize a sequence
  whose next step continues generation exactly where the source stopped
  (the same contract as recompute-on-resume, minus the recompute).

Fault-injection sites: ``kv.export`` fires per export chunk, ``kv.import``
fires before any target-side mutation — chaos tests drive torn snapshots,
crash-mid-import and import-reject→recompute through the exact production
paths (docs/RESILIENCE.md).
"""

import dataclasses
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...resilience import fault_injection as _fi
from ...utils.logging import logger

__all__ = ["KVSnapshot", "KVExporter", "import_snapshot",
           "export_prefix", "import_prefix",
           "SnapshotError", "SnapshotIntegrityError", "SnapshotAborted",
           "KVImportError"]


class SnapshotError(RuntimeError):
    """Base class for KV snapshot export/import failures."""


class SnapshotIntegrityError(SnapshotError):
    """A staged chunk's crc32 no longer matches its payload (torn copy,
    bit rot in host staging, truncation in transit)."""


class SnapshotAborted(SnapshotError):
    """The source sequence changed out from under an in-flight export
    (preempted / flushed / resumed): the staged prefix is unusable."""


class KVImportError(SnapshotError):
    """The target engine cannot take this snapshot (geometry/dtype/token
    mismatch, no page capacity, unsupported arena layout)."""


def _crc(block: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(block).tobytes())


@dataclasses.dataclass
class KVSnapshot:
    """One sequence's host-staged KV state.

    ``tokens``/``seen_tokens`` pin WHAT the pages mean: pages ``i`` of the
    export order hold the KV of token positions ``[i*page_size,
    (i+1)*page_size)`` of ``tokens``, valid through ``seen_tokens``.
    ``block_shape`` is the arena's per-page geometry ``(L, page_size, 2,
    n_kv, head_dim)`` and ``dtype`` its element type — both must match the
    importing arena exactly.  ``chunks`` are the staged blocks in export
    order (``[L, n_i, page, 2, n_kv, hd]`` each) with one crc32 per chunk;
    ``complete`` flips only after the LAST chunk landed, so a partially
    exported snapshot (source died mid-flight) is structurally unusable."""
    tokens: List[int]
    seen_tokens: int
    page_size: int
    block_shape: Tuple[int, ...]
    dtype: str
    chunks: List[np.ndarray] = dataclasses.field(default_factory=list)
    crcs: List[int] = dataclasses.field(default_factory=list)
    complete: bool = False
    source: Optional[str] = None          # provenance tag (replica id), logs only

    @property
    def n_pages(self) -> int:
        return sum(int(c.shape[1]) for c in self.chunks)

    @property
    def n_bytes(self) -> int:
        return sum(int(c.nbytes) for c in self.chunks)

    def add_chunk(self, block: np.ndarray) -> None:
        self.chunks.append(block)
        self.crcs.append(_crc(block))

    def verify(self) -> None:
        """Re-checksum every staged chunk; raises on any mismatch.  An
        incomplete snapshot fails here too — importing a prefix of a
        sequence's KV would silently attend to garbage for the tail."""
        if not self.complete:
            raise SnapshotIntegrityError(
                f"snapshot incomplete: {self.n_pages} page(s) staged, export "
                "never finished")
        for i, (block, crc) in enumerate(zip(self.chunks, self.crcs)):
            if _crc(block) != crc:
                raise SnapshotIntegrityError(
                    f"snapshot chunk {i} crc mismatch "
                    f"({block.shape[1]} page(s)) — torn or corrupted staging")


class KVExporter:
    """Chunked device→host export of one paused sequence's KV pages.

    Construction snapshots the sequence's identity (token history, seen
    boundary, page list) — the caller pauses the sequence first, so these
    are stable for the export's lifetime.  Each :meth:`step_chunk` stages
    the next ``chunk_pages`` pages through
    :meth:`~....inference.v2.ragged.BlockedKVCache.export_pages` and
    returns True once the snapshot is complete; the fleet driver calls it
    once per round so the d2h copies overlap the source replica's ongoing
    decode steps for everything else it is serving."""

    def __init__(self, engine, uid: int, chunk_pages: int = 4,
                 source: Optional[str] = None):
        if chunk_pages < 1:
            raise ValueError(f"chunk_pages must be >= 1, got {chunk_pages}")
        seq = engine.state.seqs[uid]
        kv = engine.kv
        arena = engine.cache
        if not hasattr(arena, "shape") or len(arena.shape) != 6:
            raise KVImportError(
                "KV export supports the scanned single-arena layout only "
                "(unroll_layers builds a per-layer tuple)")
        self.engine = engine
        self.uid = uid
        self.chunk_pages = int(chunk_pages)
        self._seq = seq
        # pages covering [0, seen_tokens): the trailing partial page is
        # exported whole — positions past ``seen_tokens`` inside it are
        # never attended on the importer either (kernels mask at start_pos)
        n_pages = -(-seq.seen_tokens // kv.page_size)
        self._pages = list(seq.pages[:n_pages])
        self._next = 0
        self.snapshot = KVSnapshot(
            tokens=list(seq.tokens), seen_tokens=seq.seen_tokens,
            page_size=kv.page_size,
            block_shape=(arena.shape[0], ) + tuple(arena.shape[2:]),
            dtype=str(arena.dtype), source=source)

    @property
    def remaining_pages(self) -> int:
        return len(self._pages) - self._next

    def _check_source(self) -> None:
        seq = self.engine.state.seqs.get(self.uid)
        if seq is not self._seq or not seq.paused or seq.done:
            raise SnapshotAborted(
                f"uid {self.uid}: source sequence preempted/flushed/resumed "
                "mid-export — staged prefix unusable")
        if seq.pages[:len(self._pages)] != self._pages:
            raise SnapshotAborted(
                f"uid {self.uid}: source page table changed mid-export")

    def step_chunk(self) -> bool:
        """Stage the next chunk; returns True when the snapshot completed.
        Idempotent after completion."""
        if self.snapshot.complete:
            return True
        _fi.check("kv.export")   # chaos site: torn/failed d2h staging
        self._check_source()
        lo = self._next
        hi = min(lo + self.chunk_pages, len(self._pages))
        if hi > lo:
            block = self.engine.kv.export_pages(self.engine.cache,
                                                self._pages[lo:hi])
            self.snapshot.add_chunk(block)
        self._next = hi
        if self._next >= len(self._pages):
            self.snapshot.complete = True
        return self.snapshot.complete


def _validate_arena(snapshot: "KVSnapshot", kv, arena) -> None:
    """The importability gate BOTH import paths (migration sequence,
    prefix adoption) share: scanned single-arena layout, matching page
    geometry and dtype.  One rule — a future layout change cannot diverge
    the two paths."""
    if not hasattr(arena, "shape") or len(arena.shape) != 6:
        raise KVImportError("KV import supports the scanned single-arena "
                            "layout only (unroll_layers builds a tuple)")
    if snapshot.page_size != kv.page_size:
        raise KVImportError(f"page_size mismatch: snapshot {snapshot.page_size} "
                            f"vs engine {kv.page_size}")
    want = (arena.shape[0], ) + tuple(arena.shape[2:])
    if tuple(snapshot.block_shape) != want:
        raise KVImportError(f"arena geometry mismatch: snapshot "
                            f"{tuple(snapshot.block_shape)} vs engine {want}")
    if snapshot.dtype != str(arena.dtype):
        raise KVImportError(f"arena dtype mismatch: snapshot {snapshot.dtype} "
                            f"vs engine {arena.dtype}")


def import_snapshot(engine, uid: int, tokens: Sequence[int],
                    snapshot: KVSnapshot, max_new_tokens: int):
    """Materialize ``snapshot`` as sequence ``uid`` on ``engine``: verify
    integrity, validate geometry, allocate fresh pages, scatter the staged
    blocks host→device, and register a descriptor whose next step continues
    generation exactly where the source stopped.

    ``tokens`` is the caller's authoritative history (``prompt + tokens
    generated so far``) and must equal the snapshot's — a snapshot carrying
    a different history would resume the wrong request.  Raises a
    :class:`SnapshotError` subclass on any rejection; the caller falls back
    to the recompute-on-resume token path.  On failure nothing leaks: pages
    are allocated only after every validation and freed if the scatter
    itself fails, so allocator refcounts never drift."""
    _fi.check("kv.import")   # chaos site: crash/device-loss mid-import
    snapshot.verify()
    kv = engine.kv
    arena = engine.cache
    _validate_arena(snapshot, kv, arena)
    if list(snapshot.tokens) != [int(t) for t in tokens]:
        raise KVImportError("token history mismatch: snapshot does not carry "
                            "this request's prompt + generated tokens")
    if uid in engine.state.seqs:
        raise KVImportError(f"uid {uid} already live on the target engine")
    n = snapshot.n_pages
    if n != -(-snapshot.seen_tokens // kv.page_size):
        raise KVImportError(f"snapshot pages ({n}) do not cover its seen "
                            f"boundary ({snapshot.seen_tokens})")
    if n > kv.max_pages_per_seq:
        raise KVImportError(f"snapshot needs {n} pages > max_pages_per_seq="
                            f"{kv.max_pages_per_seq}")
    shortfall = n - kv.allocator.free_pages
    if shortfall > 0 and kv.prefix_cache is not None:
        kv.prefix_cache.evict(shortfall)
        shortfall = n - kv.allocator.free_pages
    if shortfall > 0:
        raise KVImportError(f"target arena short {shortfall} page(s) for the "
                            f"{n}-page import")
    from ...inference.v2.ragged import SequenceDescriptor
    pages = kv.allocator.allocate(n)
    try:
        new_arena = arena
        off = 0
        for block in snapshot.chunks:
            cnt = int(block.shape[1])
            new_arena = kv.import_pages(new_arena, pages[off:off + cnt], block)
            off += cnt
    except BaseException:
        kv.allocator.free(pages)
        raise
    engine.cache = new_arena
    seq = SequenceDescriptor(uid=uid, tokens=list(snapshot.tokens), pages=pages,
                             seen_tokens=snapshot.seen_tokens)
    engine.state.seqs[uid] = seq
    engine._max_new[uid] = int(max_new_tokens)
    # publish the imported full pages to the target's prefix cache: the
    # decode replica becomes warm for affinity routing exactly as if it had
    # prefilled the prompt itself
    engine.state.note_progress(seq)
    logger.debug(f"kvtransfer: imported uid={uid} ({n} pages, "
                 f"{snapshot.n_bytes} bytes, source={snapshot.source})")
    return seq


# --------------------------------------------------------- prefix transfer
#
# The fleet prefix directory's hot-prefix import (docs/SERVING.md "Prefix
# directory"): unlike a migration snapshot — one request's whole KV state,
# consumed by resuming that request — a PREFIX snapshot carries only the
# immutable FULL pages of a shared prompt prefix, and its consumer is the
# target replica's PrefixCacheManager: the pages are adopted as cache
# entries so the NEXT admission's match() attaches them, exactly as if the
# target had prefilled the prompt itself.  Same staleness stance as the
# migration ladder: every rejection falls back to recompute, never to
# wrong KV.


def export_prefix(engine, tokens: Sequence[int],
                  source: Optional[str] = None) -> Optional["KVSnapshot"]:
    """Stage the full prefix-cache pages ``engine`` holds for ``tokens``
    device→host as a complete :class:`KVSnapshot` (tokens truncated to the
    staged depth).  Returns None when the engine holds nothing usable —
    the evict-after-publish staleness race: the directory promised warmth
    the donor has since evicted, and the caller's recompute fallback owns
    the request.  Read-only on the donor: no refcounts taken, no LRU
    touched (the donor never sees this request).  The ``kv.export`` chaos
    site fires once per staging, like a migration chunk.

    When the donor has a host KV tier attached (``serving/kvtier``), the
    staged run is EXTENDED with warm-on-host pages continuing the chain
    past the device-held depth: those blocks are already host-side
    (crc-verified on read), so a saturated-warm donor can serve the import
    without touching its device arena at all."""
    kv = engine.kv
    pc = kv.prefix_cache
    arena = engine.cache
    if pc is None or not hasattr(arena, "shape") or len(arena.shape) != 6:
        return None
    pages = [page for _, page in pc._walk(tokens)]
    tier = getattr(engine, "_kv_tier", None)
    host_blocks = []
    if tier is not None:
        # the same usable cap _walk applies: never stage a page covering
        # the final token (the importer must still compute >= 1 token)
        max_depth = max(0, (len(tokens) - 1) // kv.page_size)
        host_blocks = tier.host_prefix_blocks(tokens, start_depth=len(pages),
                                              max_depth=max_depth)
    if not pages and not host_blocks:
        return None
    _fi.check("kv.export")   # chaos site: torn/failed d2h staging
    depth = len(pages) + len(host_blocks)
    snapshot = KVSnapshot(
        tokens=[int(t) for t in tokens[:depth * kv.page_size]],
        seen_tokens=depth * kv.page_size, page_size=kv.page_size,
        block_shape=(arena.shape[0], ) + tuple(arena.shape[2:]),
        dtype=str(arena.dtype), source=source)
    if pages:
        snapshot.add_chunk(kv.export_pages(arena, pages))
    for block in host_blocks:
        snapshot.add_chunk(block)
    snapshot.complete = True
    return snapshot


def import_prefix(engine, snapshot: "KVSnapshot") -> int:
    """Adopt ``snapshot``'s full prefix pages into ``engine``'s prefix
    cache: verify integrity, validate geometry, allocate pages for the
    MISSING tail of the chain (pages the target already holds are skipped),
    scatter host→device, and publish the chain entries so the next
    admission's ``match()`` attaches them.  Returns pages imported (0 =
    target already warm).  Raises a :class:`SnapshotError` subclass on any
    rejection — the caller dispatches cold and the ordinary prefill
    recomputes; torn staging is caught by ``verify()`` here, never decoded
    into wrong KV.  On failure nothing leaks: pages are allocated after
    every validation and freed if the scatter fails."""
    _fi.check("prefix.import")   # chaos site: crash/device-loss mid-import
    snapshot.verify()
    kv = engine.kv
    pc = kv.prefix_cache
    arena = engine.cache
    if pc is None:
        raise KVImportError("target engine has no prefix cache")
    _validate_arena(snapshot, kv, arena)
    n = snapshot.n_pages
    if n * kv.page_size != len(snapshot.tokens) \
            or snapshot.seen_tokens != len(snapshot.tokens):
        raise KVImportError(
            f"prefix snapshot must carry exactly its full pages' tokens: "
            f"{n} page(s) vs {len(snapshot.tokens)} token(s), seen "
            f"{snapshot.seen_tokens}")
    # pages the target already published are skipped — held entries along
    # one chain are always a prefix run (register/adopt insert root→leaf,
    # eviction removes leaves), so the missing set is a contiguous tail
    have = pc.held_depth(snapshot.tokens)
    if have >= n:
        return 0
    shortfall = (n - have) - kv.allocator.free_pages
    if shortfall > 0:
        pc.evict(shortfall)
        # the LRU sweep may have evicted THIS chain's own held prefix —
        # recompute the boundary, or the adopted tail would hang off a
        # hole in the chain and match() could never reach it
        have = pc.held_depth(snapshot.tokens)
    missing = n - have
    shortfall = missing - kv.allocator.free_pages
    if shortfall > 0:
        raise KVImportError(f"target arena short {shortfall} page(s) for the "
                            f"{missing}-page prefix import")
    block = snapshot.chunks[0] if len(snapshot.chunks) == 1 \
        else np.concatenate(snapshot.chunks, axis=1)
    pages = kv.allocator.allocate(missing)
    try:
        engine.cache = kv.import_pages(engine.cache, pages,
                                       np.ascontiguousarray(block[:, have:n]))
    except BaseException:
        kv.allocator.free(pages)
        raise
    # ownership of the allocation's refcounts transfers to the cache
    pc.adopt(snapshot.tokens, have, pages)
    logger.debug(f"kvtransfer: prefix import of {missing} page(s) "
                 f"(held {have}, source={snapshot.source})")
    return missing
