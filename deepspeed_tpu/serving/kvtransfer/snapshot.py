"""Host-staged KV migration: export a sequence's paged KV device→host,
carry it as a crc-tagged :class:`KVSnapshot`, and import it into another
engine's arena so decode resumes there with byte-identical outputs.

This is the serving-side application of PAPER.md's L6 host-staging
machinery (``swap_tensor`` / host-memory-kind shardings — the
ZeRO-Offload/Infinity mapping): instead of optimizer shards, the staged
payload is a request's KV pages, and the consumer is another replica of
the fleet (DistServe-style prefill/decode disaggregation, Splitwise-style
phase splitting — see docs/SERVING.md "Disaggregated serving").

Protocol pieces:

* :class:`KVSnapshot` — the host-side container: the sequence's full token
  history + seen boundary at export time, the arena's per-page geometry,
  and the staged page blocks in export order, each crc32-tagged.
  ``verify()`` re-checksums every chunk; a torn or bit-rotted snapshot is
  rejected at import (→ the caller's recompute fallback), never silently
  decoded into wrong KV.
* :class:`KVExporter` — incremental device→host export of one PAUSED
  sequence, ``chunk_pages`` pages per :meth:`step_chunk` call, so a fleet
  driver interleaves export chunks with the source replica's ongoing
  decode steps instead of stalling them behind one bulk d2h.  The source
  sequence must stay paused and intact between chunks; if it was preempted
  (pages released) mid-flight the exporter raises :class:`SnapshotAborted`
  and the caller falls back to the token path.
* :func:`import_snapshot` — allocate fresh pages on the target engine,
  scatter the staged blocks into its arena, and materialize a sequence
  whose next step continues generation exactly where the source stopped
  (the same contract as recompute-on-resume, minus the recompute).

Fault-injection sites: ``kv.export`` fires per export chunk, ``kv.import``
fires before any target-side mutation — chaos tests drive torn snapshots,
crash-mid-import and import-reject→recompute through the exact production
paths (docs/RESILIENCE.md).
"""

import dataclasses
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ...resilience import fault_injection as _fi
from ...utils.logging import logger

__all__ = ["KVSnapshot", "KVExporter", "import_snapshot",
           "SnapshotError", "SnapshotIntegrityError", "SnapshotAborted",
           "KVImportError"]


class SnapshotError(RuntimeError):
    """Base class for KV snapshot export/import failures."""


class SnapshotIntegrityError(SnapshotError):
    """A staged chunk's crc32 no longer matches its payload (torn copy,
    bit rot in host staging, truncation in transit)."""


class SnapshotAborted(SnapshotError):
    """The source sequence changed out from under an in-flight export
    (preempted / flushed / resumed): the staged prefix is unusable."""


class KVImportError(SnapshotError):
    """The target engine cannot take this snapshot (geometry/dtype/token
    mismatch, no page capacity, unsupported arena layout)."""


def _crc(block: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(block).tobytes())


@dataclasses.dataclass
class KVSnapshot:
    """One sequence's host-staged KV state.

    ``tokens``/``seen_tokens`` pin WHAT the pages mean: pages ``i`` of the
    export order hold the KV of token positions ``[i*page_size,
    (i+1)*page_size)`` of ``tokens``, valid through ``seen_tokens``.
    ``block_shape`` is the arena's per-page geometry ``(L, page_size, 2,
    n_kv, head_dim)`` and ``dtype`` its element type — both must match the
    importing arena exactly.  ``chunks`` are the staged blocks in export
    order (``[L, n_i, page, 2, n_kv, hd]`` each) with one crc32 per chunk;
    ``complete`` flips only after the LAST chunk landed, so a partially
    exported snapshot (source died mid-flight) is structurally unusable."""
    tokens: List[int]
    seen_tokens: int
    page_size: int
    block_shape: Tuple[int, ...]
    dtype: str
    chunks: List[np.ndarray] = dataclasses.field(default_factory=list)
    crcs: List[int] = dataclasses.field(default_factory=list)
    complete: bool = False
    source: Optional[str] = None          # provenance tag (replica id), logs only

    @property
    def n_pages(self) -> int:
        return sum(int(c.shape[1]) for c in self.chunks)

    @property
    def n_bytes(self) -> int:
        return sum(int(c.nbytes) for c in self.chunks)

    def add_chunk(self, block: np.ndarray) -> None:
        self.chunks.append(block)
        self.crcs.append(_crc(block))

    def verify(self) -> None:
        """Re-checksum every staged chunk; raises on any mismatch.  An
        incomplete snapshot fails here too — importing a prefix of a
        sequence's KV would silently attend to garbage for the tail."""
        if not self.complete:
            raise SnapshotIntegrityError(
                f"snapshot incomplete: {self.n_pages} page(s) staged, export "
                "never finished")
        for i, (block, crc) in enumerate(zip(self.chunks, self.crcs)):
            if _crc(block) != crc:
                raise SnapshotIntegrityError(
                    f"snapshot chunk {i} crc mismatch "
                    f"({block.shape[1]} page(s)) — torn or corrupted staging")


class KVExporter:
    """Chunked device→host export of one paused sequence's KV pages.

    Construction snapshots the sequence's identity (token history, seen
    boundary, page list) — the caller pauses the sequence first, so these
    are stable for the export's lifetime.  Each :meth:`step_chunk` stages
    the next ``chunk_pages`` pages through
    :meth:`~....inference.v2.ragged.BlockedKVCache.export_pages` and
    returns True once the snapshot is complete; the fleet driver calls it
    once per round so the d2h copies overlap the source replica's ongoing
    decode steps for everything else it is serving."""

    def __init__(self, engine, uid: int, chunk_pages: int = 4,
                 source: Optional[str] = None):
        if chunk_pages < 1:
            raise ValueError(f"chunk_pages must be >= 1, got {chunk_pages}")
        seq = engine.state.seqs[uid]
        kv = engine.kv
        arena = engine.cache
        if not hasattr(arena, "shape") or len(arena.shape) != 6:
            raise KVImportError(
                "KV export supports the scanned single-arena layout only "
                "(unroll_layers builds a per-layer tuple)")
        self.engine = engine
        self.uid = uid
        self.chunk_pages = int(chunk_pages)
        self._seq = seq
        # pages covering [0, seen_tokens): the trailing partial page is
        # exported whole — positions past ``seen_tokens`` inside it are
        # never attended on the importer either (kernels mask at start_pos)
        n_pages = -(-seq.seen_tokens // kv.page_size)
        self._pages = list(seq.pages[:n_pages])
        self._next = 0
        self.snapshot = KVSnapshot(
            tokens=list(seq.tokens), seen_tokens=seq.seen_tokens,
            page_size=kv.page_size,
            block_shape=(arena.shape[0], ) + tuple(arena.shape[2:]),
            dtype=str(arena.dtype), source=source)

    @property
    def remaining_pages(self) -> int:
        return len(self._pages) - self._next

    def _check_source(self) -> None:
        seq = self.engine.state.seqs.get(self.uid)
        if seq is not self._seq or not seq.paused or seq.done:
            raise SnapshotAborted(
                f"uid {self.uid}: source sequence preempted/flushed/resumed "
                "mid-export — staged prefix unusable")
        if seq.pages[:len(self._pages)] != self._pages:
            raise SnapshotAborted(
                f"uid {self.uid}: source page table changed mid-export")

    def step_chunk(self) -> bool:
        """Stage the next chunk; returns True when the snapshot completed.
        Idempotent after completion."""
        if self.snapshot.complete:
            return True
        _fi.check("kv.export")   # chaos site: torn/failed d2h staging
        self._check_source()
        lo = self._next
        hi = min(lo + self.chunk_pages, len(self._pages))
        if hi > lo:
            block = self.engine.kv.export_pages(self.engine.cache,
                                                self._pages[lo:hi])
            self.snapshot.add_chunk(block)
        self._next = hi
        if self._next >= len(self._pages):
            self.snapshot.complete = True
        return self.snapshot.complete


def import_snapshot(engine, uid: int, tokens: Sequence[int],
                    snapshot: KVSnapshot, max_new_tokens: int):
    """Materialize ``snapshot`` as sequence ``uid`` on ``engine``: verify
    integrity, validate geometry, allocate fresh pages, scatter the staged
    blocks host→device, and register a descriptor whose next step continues
    generation exactly where the source stopped.

    ``tokens`` is the caller's authoritative history (``prompt + tokens
    generated so far``) and must equal the snapshot's — a snapshot carrying
    a different history would resume the wrong request.  Raises a
    :class:`SnapshotError` subclass on any rejection; the caller falls back
    to the recompute-on-resume token path.  On failure nothing leaks: pages
    are allocated only after every validation and freed if the scatter
    itself fails, so allocator refcounts never drift."""
    _fi.check("kv.import")   # chaos site: crash/device-loss mid-import
    snapshot.verify()
    kv = engine.kv
    arena = engine.cache
    if not hasattr(arena, "shape") or len(arena.shape) != 6:
        raise KVImportError("KV import supports the scanned single-arena "
                            "layout only (unroll_layers builds a tuple)")
    if snapshot.page_size != kv.page_size:
        raise KVImportError(f"page_size mismatch: snapshot {snapshot.page_size} "
                            f"vs engine {kv.page_size}")
    want = (arena.shape[0], ) + tuple(arena.shape[2:])
    if tuple(snapshot.block_shape) != want:
        raise KVImportError(f"arena geometry mismatch: snapshot "
                            f"{tuple(snapshot.block_shape)} vs engine {want}")
    if snapshot.dtype != str(arena.dtype):
        raise KVImportError(f"arena dtype mismatch: snapshot {snapshot.dtype} "
                            f"vs engine {arena.dtype}")
    if list(snapshot.tokens) != [int(t) for t in tokens]:
        raise KVImportError("token history mismatch: snapshot does not carry "
                            "this request's prompt + generated tokens")
    if uid in engine.state.seqs:
        raise KVImportError(f"uid {uid} already live on the target engine")
    n = snapshot.n_pages
    if n != -(-snapshot.seen_tokens // kv.page_size):
        raise KVImportError(f"snapshot pages ({n}) do not cover its seen "
                            f"boundary ({snapshot.seen_tokens})")
    if n > kv.max_pages_per_seq:
        raise KVImportError(f"snapshot needs {n} pages > max_pages_per_seq="
                            f"{kv.max_pages_per_seq}")
    shortfall = n - kv.allocator.free_pages
    if shortfall > 0 and kv.prefix_cache is not None:
        kv.prefix_cache.evict(shortfall)
        shortfall = n - kv.allocator.free_pages
    if shortfall > 0:
        raise KVImportError(f"target arena short {shortfall} page(s) for the "
                            f"{n}-page import")
    from ...inference.v2.ragged import SequenceDescriptor
    pages = kv.allocator.allocate(n)
    try:
        new_arena = arena
        off = 0
        for block in snapshot.chunks:
            cnt = int(block.shape[1])
            new_arena = kv.import_pages(new_arena, pages[off:off + cnt], block)
            off += cnt
    except BaseException:
        kv.allocator.free(pages)
        raise
    engine.cache = new_arena
    seq = SequenceDescriptor(uid=uid, tokens=list(snapshot.tokens), pages=pages,
                             seen_tokens=snapshot.seen_tokens)
    engine.state.seqs[uid] = seq
    engine._max_new[uid] = int(max_new_tokens)
    # publish the imported full pages to the target's prefix cache: the
    # decode replica becomes warm for affinity routing exactly as if it had
    # prefilled the prompt itself
    engine.state.note_progress(seq)
    logger.debug(f"kvtransfer: imported uid={uid} ({n} pages, "
                 f"{snapshot.n_bytes} bytes, source={snapshot.source})")
    return seq
