"""KV page export/import for cross-replica migration (docs/SERVING.md
"Disaggregated serving").

Device↔host staging of a request's paged KV state: a crc-tagged
:class:`KVSnapshot` container, a chunked :class:`KVExporter` whose d2h
copies overlap the source replica's ongoing decode steps, and
:func:`import_snapshot` to resume decode on another engine with
byte-identical outputs.  Fault sites ``kv.export`` / ``kv.import`` wrap
the staging edges (docs/RESILIENCE.md).
"""

from .snapshot import (KVExporter, KVImportError, KVSnapshot, SnapshotAborted,
                       SnapshotError, SnapshotIntegrityError, import_snapshot)

__all__ = [
    "KVExporter", "KVImportError", "KVSnapshot", "SnapshotAborted",
    "SnapshotError", "SnapshotIntegrityError", "import_snapshot",
]
