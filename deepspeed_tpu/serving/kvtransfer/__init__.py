"""KV page export/import for cross-replica migration (docs/SERVING.md
"Disaggregated serving").

Device↔host staging of a request's paged KV state: a crc-tagged
:class:`KVSnapshot` container, a chunked :class:`KVExporter` whose d2h
copies overlap the source replica's ongoing decode steps, and
:func:`import_snapshot` to resume decode on another engine with
byte-identical outputs.  :func:`export_prefix` / :func:`import_prefix`
carry the same machinery for SHARED-PREFIX pages: immutable full pages of
a hot prompt prefix staged once and adopted into a cold replica's prefix
cache (docs/SERVING.md "Prefix directory").  Fault sites ``kv.export`` /
``kv.import`` / ``prefix.import`` wrap the staging edges
(docs/RESILIENCE.md).
"""

from .snapshot import (KVExporter, KVImportError, KVSnapshot, SnapshotAborted,
                       SnapshotError, SnapshotIntegrityError, export_prefix,
                       import_prefix, import_snapshot)

__all__ = [
    "KVExporter", "KVImportError", "KVSnapshot", "SnapshotAborted",
    "SnapshotError", "SnapshotIntegrityError", "export_prefix",
    "import_prefix", "import_snapshot",
]
