"""Tiered paged KV: a host (CPU-memory, optionally file-backed "nvme")
tier under :class:`~...inference.v2.ragged.BlockedKVCache`.

PAPER.md's L6 swap layer (``runtime/swap_tensor/`` — the ZeRO-Offload/
Infinity blueprint) applied to inference state: the device arena is the
hardest capacity wall in the fleet, and today every cold sequence either
squats in HBM or is evicted and recomputed from scratch.  This module adds
the missing rung between those extremes:

* **Demotion** — a cold sequence's KV pages (or a cold prefix-cache
  chain's pages) are staged device→host as crc-tagged
  :class:`~..kvtransfer.KVSnapshot` chunks, reusing the r13 ``kvtransfer``
  gather path (``BlockedKVCache.export_pages``).  The device pages are
  then released; the host copy is the sequence's state of record.
* **Promotion** — the host pages are scattered back (``import_pages`` via
  ``kvtransfer.import_snapshot``) when the sequence resumes.  The h2d
  transfer is issued as a **double-buffered prefetch** ahead of admission
  (``prefetch_depth`` concurrent transfers), so under the virtual clock's
  cost model it hides under the intervening device windows — the same
  upload/compute overlap discipline as r6's ``HostStreamedOptimizer``.
  Only the non-hidden remainder stalls admission, and it is attributed
  (``phase/promote`` spans, the ``promote_wait`` step-anatomy segment,
  the ``kv/tier_prefetch_hidden_frac`` gauge).
* **Fallback ladder** — every host-tier miss or fault degrades to the
  recompute-on-resume path the serving engine already has: slower, never
  wrong.  A torn or bit-rotted host page is rejected by the snapshot crc
  *before* any scatter.

Fault-injection sites: ``kv.demote`` fires per demotion (sequence or
prefix page), ``kv.promote`` per promotion claim — ``os_error`` at either
degrades to eviction/recompute; ``InjectedCrash`` and ``DeviceLossError``
propagate (docs/RESILIENCE.md).
"""

import dataclasses
import os
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from ...inference.v2.ragged import prefix_chain_hashes
from ...resilience import fault_injection as _fi
from ...resilience.fault_injection import DeviceLossError, InjectedCrash
from ...utils.logging import logger
from ..kvtransfer import KVSnapshot

__all__ = ["TierConfig", "HostKVHandle", "HostKVTier", "TieredKVManager"]

# kinds the tier's degradable-failure handling must never absorb:
# simulated driver death and injected device loss re-raise through every
# tier edge (chaos tests assert this)
_FATAL = (InjectedCrash, DeviceLossError)


@dataclasses.dataclass(frozen=True)
class TierConfig:
    #: host-tier capacity in KV pages (sequence snapshots + prefix pages
    #: combined).  The tier LRU-evicts its own entries to stay under it;
    #: an evicted parked entry silently degrades that resume to recompute.
    host_capacity_pages: int = 256
    #: h2d promotion cost, clock-seconds per page (VirtualClock cost
    #: model).  0.0 — the default — makes promotion free, so every
    #: existing golden is unchanged; benches set it nonzero to measure the
    #: prefetch-hidden fraction.
    h2d_page_s: float = 0.0
    #: concurrent promotion transfers (double buffering, the r6
    #: discipline): a third prefetch issued while two are in flight starts
    #: when the oldest of the two completes.
    prefetch_depth: int = 2
    #: demote prefix-cache pages evicted under pressure to the host tier
    #: (the warm-on-host prefix tier); sequence park/preempt demotion is
    #: always on.
    demote_prefix: bool = True
    #: file-backed "nvme" mode: when set, staged chunk bytes live in this
    #: directory instead of host RAM (crcs and geometry stay in memory, so
    #: torn files are still rejected at promote).  None = CPU memory.
    spill_dir: Optional[str] = None
    #: capacity-pressure demotion watermarks (ROADMAP kvtier depth item):
    #: occupancy fractions in [0, 1].  When DEVICE arena occupancy
    #: (allocated / usable pages) reaches ``device_watermark_hi``,
    #: :meth:`TieredKVManager.enforce_watermarks` demotes coldest-first —
    #: LRU-leaf prefix-cache pages, staged host-side via the demoter hook
    #: — until occupancy is back at ``device_watermark_lo`` (hysteresis:
    #: nothing happens between lo and hi, so the sweep never thrashes at
    #: the boundary).  Likewise ``host_watermark_hi``/``lo`` bound the
    #: HOST tier by dropping its LRU-coldest entries (a dropped parked
    #: snapshot degrades that resume to recompute — slower, never wrong).
    #: None (the default) disables that side entirely; every pre-existing
    #: golden is unchanged.
    device_watermark_hi: Optional[float] = None
    device_watermark_lo: Optional[float] = None
    host_watermark_hi: Optional[float] = None
    host_watermark_lo: Optional[float] = None

    def __post_init__(self):
        for hi, lo in ((self.device_watermark_hi, self.device_watermark_lo),
                       (self.host_watermark_hi, self.host_watermark_lo)):
            if hi is not None:
                assert lo is not None and 0.0 <= lo <= hi <= 1.0, \
                    f"watermarks need 0 <= lo <= hi <= 1, got lo={lo} hi={hi}"


class HostKVHandle:
    """What rides on ``ServingRequest.kv_snapshot`` for a parked/demoted
    request: a *name* for the host-tier entry, not the bytes — the tier
    owns the snapshot (and may LRU-evict it, degrading the resume to
    recompute).  The serving engine resolves the handle at admission via
    :meth:`TieredKVManager.claim`."""

    __slots__ = ("uid", "n_pages", "tier")

    def __init__(self, uid: int, n_pages: int, tier: "TieredKVManager"):
        self.uid = uid
        self.n_pages = n_pages
        self.tier = tier

    def __repr__(self):
        return f"HostKVHandle(uid={self.uid}, n_pages={self.n_pages})"


class _HostPrefixPage:
    """One prefix-cache page staged host-side: the page's token tuple and
    parent digest (the same chain identity the device cache keys by) plus
    the staged block ``[L, 1, page, 2, n_kv, hd]`` and its crc."""

    __slots__ = ("tokens", "parent", "block", "crc", "shape", "dtype", "path")

    def __init__(self, tokens, parent, block, crc, shape, dtype, path=None):
        self.tokens = tokens
        self.parent = parent
        self.block = block      # None in spill mode (bytes live at ``path``)
        self.crc = crc
        self.shape = shape
        self.dtype = dtype
        self.path = path


class HostKVTier:
    """Bounded host page store: sequence snapshots keyed by uid, prefix
    pages keyed by chain digest, one LRU across both kinds.  Capacity is
    counted in pages; inserting evicts LRU entries until the newcomer
    fits (an entry larger than the whole tier is refused)."""

    def __init__(self, capacity_pages: int, spill_dir: Optional[str] = None):
        if capacity_pages < 1:
            raise ValueError(f"host tier needs >= 1 page, got {capacity_pages}")
        self.capacity_pages = int(capacity_pages)
        self.spill_dir = spill_dir
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        #: uid -> complete KVSnapshot (chunk bytes on disk in spill mode)
        self._seq: Dict[int, KVSnapshot] = {}
        #: chain digest -> _HostPrefixPage
        self._prefix: Dict[int, _HostPrefixPage] = {}
        #: unified LRU: ("seq", uid) / ("px", digest) -> n_pages
        self._lru: "OrderedDict[Tuple[str, int], int]" = OrderedDict()
        self.pages_used = 0
        self.stats = {"seq_put": 0, "seq_taken": 0, "prefix_put": 0,
                      "lru_evicted_pages": 0, "rejected_oversize": 0}
        #: optional eviction sink ``on_evict(kind, key)`` with kind
        #: "seq"/"px" — the TieredKVManager forwards prefix drops to the
        #: fleet directory as host-tier retracts
        self.on_evict = None

    # ------------------------------------------------------------ capacity

    def _evict_for(self, need: int) -> bool:
        """Make room for ``need`` pages; False when impossible."""
        if need > self.capacity_pages:
            self.stats["rejected_oversize"] += 1
            return False
        while self.pages_used + need > self.capacity_pages:
            victim = next(iter(self._lru), None)
            if victim is None:
                return False
            self._drop(victim)
            self.stats["lru_evicted_pages"] += 1
        return True

    def _drop(self, key: Tuple[str, int]) -> None:
        n = self._lru.pop(key)
        self.pages_used -= n
        kind, ident = key
        if kind == "seq":
            snap = self._seq.pop(ident)
            self._unlink(p for p, _, _ in getattr(snap, "_spill_meta", ()))
        else:
            ent = self._prefix.pop(ident)
            self._unlink([ent.path] if ent.path else ())
        if self.on_evict is not None:
            self.on_evict(kind, ident)

    def _unlink(self, paths) -> None:
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass

    # ----------------------------------------------------------- sequences

    def put_seq(self, uid: int, snapshot: KVSnapshot) -> bool:
        """Store (or replace) the parked snapshot for ``uid``; False when
        it cannot fit even after LRU eviction (caller degrades to plain
        eviction/recompute)."""
        key = ("seq", uid)
        if key in self._lru:
            self._drop(key)
        n = snapshot.n_pages
        if not self._evict_for(n):
            return False
        if self.spill_dir is not None:
            self._spill_seq(uid, snapshot)
        self._seq[uid] = snapshot
        self._lru[key] = n
        self.pages_used += n
        self.stats["seq_put"] += 1
        return True

    def peek_seq(self, uid: int) -> Optional[KVSnapshot]:
        snap = self._seq.get(uid)
        if snap is not None:
            self._lru.move_to_end(("seq", uid))
        return snap

    def take_seq(self, uid: int) -> Optional[KVSnapshot]:
        """Remove and return ``uid``'s snapshot, loading spilled chunk
        bytes back into memory; None when absent (LRU-evicted — that
        resume recomputes)."""
        if uid not in self._seq:
            return None
        n = self._lru.pop(("seq", uid))
        self.pages_used -= n
        snap = self._seq.pop(uid)
        meta = getattr(snap, "_spill_meta", None)
        if meta:
            snap.chunks = [np.fromfile(p, dtype=np.dtype(dt)).reshape(shape)
                           for p, shape, dt in meta]
            self._unlink(p for p, _, _ in meta)
            del snap._spill_meta
        self.stats["seq_taken"] += 1
        return snap

    def discard_seq(self, uid: int) -> None:
        if uid in self._seq:
            self._drop(("seq", uid))

    # ------------------------------------------------------- prefix pages

    def put_prefix(self, digest: int, entry: _HostPrefixPage) -> bool:
        key = ("px", digest)
        if key in self._lru:
            self._drop(key)
        if not self._evict_for(1):
            return False
        if self.spill_dir is not None and entry.block is not None:
            entry.path = os.path.join(
                self.spill_dir, f"px_{digest & 0xFFFFFFFFFFFFFFFF:016x}.bin")
            _write_file(entry.path, np.ascontiguousarray(entry.block).tobytes())
            entry.block = None
        self._prefix[digest] = entry
        self._lru[key] = 1
        self.pages_used += 1
        self.stats["prefix_put"] += 1
        return True

    def get_prefix(self, digest: int) -> Optional[_HostPrefixPage]:
        ent = self._prefix.get(digest)
        if ent is not None:
            self._lru.move_to_end(("px", digest))
        return ent

    def prefix_block(self, ent: _HostPrefixPage) -> np.ndarray:
        """The entry's staged block, loaded from disk in spill mode."""
        if ent.block is not None:
            return ent.block
        return np.fromfile(ent.path, dtype=np.dtype(ent.dtype)).reshape(ent.shape)

    def drop_prefix(self, digest: int) -> None:
        if digest in self._prefix:
            self._drop(("px", digest))

    def held_prefix_digests(self) -> List[int]:
        return list(self._prefix)

    # --------------------------------------------------------- spill mode

    def _spill_seq(self, uid: int, snapshot: KVSnapshot) -> None:
        meta = []
        for i, block in enumerate(snapshot.chunks):
            p = os.path.join(self.spill_dir, f"seq_{uid}_{i}.bin")
            _write_file(p, np.ascontiguousarray(block).tobytes())
            meta.append((p, tuple(block.shape), str(block.dtype)))
        snapshot._spill_meta = meta
        snapshot.chunks = []


def _write_file(path: str, data: bytes) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:  # atomic-ok: os.replace below; crcs re-verified on load
        f.write(data)
    os.replace(tmp, path)


class TieredKVManager:
    """Drives one engine's host KV tier: demotes cold sequences and cold
    prefix chains, promotes them back with prefetch, and accounts the
    overlap.  Attach via ``ServingEngine``'s ``tier`` — the frontend then
    parks/resumes requests through it and ``KVPressureManager`` prefers
    demotion over evict+recompute."""

    def __init__(self, engine, config: Optional[TierConfig] = None,
                 metrics=None):
        self.engine = engine          # the InferenceEngineV2
        self.config = config or TierConfig()
        self.metrics = metrics
        self.host = HostKVTier(self.config.host_capacity_pages,
                               spill_dir=self.config.spill_dir)
        self.host.on_evict = self._on_host_evict
        #: uid -> (t_start, t_ready, transfer_s): issued promote prefetches
        self._prefetch: Dict[int, Tuple[float, float, float]] = {}
        #: completion times of in-flight transfers (the double-buffer bound)
        self._slots: List[float] = []
        self.stats = {"demotions": 0, "promotions": 0, "demote_faults": 0,
                      "promote_faults": 0, "promote_fallbacks": 0,
                      "prefix_demotions": 0, "prefix_promotions": 0,
                      "transfer_s": 0.0, "hidden_s": 0.0,
                      "watermark_demotions": 0, "watermark_host_drops": 0}
        #: host-tier publish bus, mirroring ``PrefixCacheManager.listener``:
        #: ``listener(event, digest)`` with "host_publish" (a prefix page
        #: entered the host tier) / "host_evict" (it left) — the fleet
        #: ReplicaPool wires this to the PrefixDirectory host tier
        self.listener = None
        # hook the device prefix cache's eviction path: pages about to be
        # freed under pressure are staged host-side first (warm-on-host)
        pc = engine.kv.prefix_cache
        if pc is not None and self.config.demote_prefix:
            pc.demoter = self._demote_prefix_page
        # export_prefix (kvtransfer) reads this to extend donor staging
        # with host-resident pages — saturated-warm imports can source
        # from the host tier without touching the donor's device arena
        engine._kv_tier = self

    # ------------------------------------------------------------- helpers

    def _count(self, name: str, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter(name).inc(n)

    def _notify(self, event: str, digest: int) -> None:
        if self.listener is not None:
            self.listener(event, digest)

    def _on_host_evict(self, kind: str, ident: int) -> None:
        if kind == "px":
            self._notify("host_evict", ident)

    @property
    def hidden_frac(self) -> Optional[float]:
        """Fraction of total promotion transfer seconds that hid under
        device windows (issued-ahead prefetch); None before any charged
        promotion."""
        if self.stats["transfer_s"] <= 0:
            return None
        return self.stats["hidden_s"] / self.stats["transfer_s"]

    # ------------------------------------------------------------ demotion

    def demote_sequence(self, uid: int) -> Optional["HostKVHandle"]:
        """Stage a live sequence's KV pages to the host tier (one complete
        crc-tagged snapshot) — called BEFORE the sequence is preempted, so
        the pages are still valid to gather.  Returns a handle to ride on
        the request, or None on any degradable failure (unsupported arena
        layout, transient I/O fault, host tier full): the caller proceeds
        with plain eviction and the resume recomputes.  ``InjectedCrash``
        and ``DeviceLossError`` propagate — driver death is never absorbed."""
        seq = self.engine.state.seqs.get(uid)
        kv = self.engine.kv
        arena = self.engine.cache
        if seq is None or seq.seen_tokens <= 0 or \
                not hasattr(arena, "shape") or len(arena.shape) != 6:
            return None
        try:
            _fi.check("kv.demote")   # chaos site: failed d2h demotion
            n_pages = -(-seq.seen_tokens // kv.page_size)
            block = kv.export_pages(arena, list(seq.pages[:n_pages]))
        except _FATAL:
            raise
        except OSError as e:
            self.stats["demote_faults"] += 1
            logger.warning(f"kvtier: demotion of uid={uid} failed ({e}); "
                           "falling back to evict+recompute")
            return None
        snapshot = KVSnapshot(
            tokens=list(seq.tokens), seen_tokens=seq.seen_tokens,
            page_size=kv.page_size,
            block_shape=(arena.shape[0],) + tuple(arena.shape[2:]),
            dtype=str(arena.dtype), source="kvtier")
        snapshot.add_chunk(block)
        snapshot.complete = True
        if not self.host.put_seq(uid, snapshot):
            self.stats["demote_faults"] += 1
            logger.warning(f"kvtier: host tier cannot hold uid={uid} "
                           f"({snapshot.n_pages} pages); evict+recompute")
            return None
        self.stats["demotions"] += 1
        self._count("kv/demote")
        return HostKVHandle(uid, snapshot.n_pages, self)

    def handle_for(self, uid: int) -> Optional["HostKVHandle"]:
        """A fresh handle for ``uid``'s parked host entry, if it still
        exists (the pressure path demotes inside ``KVPressureManager.
        resolve``; the frontend picks the handle up in ``_on_preempted``)."""
        snap = self.host.peek_seq(uid)
        if snap is None:
            return None
        return HostKVHandle(uid, snap.n_pages, self)

    def discard(self, uid: int) -> None:
        """Drop ``uid``'s host entry and any pending prefetch (the request
        reached a terminal without resuming)."""
        self.host.discard_seq(uid)
        self._prefetch.pop(uid, None)

    def enforce_watermarks(self) -> Dict[str, int]:
        """Capacity-pressure demotion: act when either tier's occupancy
        crosses its configured HIGH watermark, demote/drop **coldest
        first**, and stop once occupancy is back at the LOW watermark —
        classic hysteresis, so a tier sitting between lo and hi is never
        touched and the sweep cannot thrash at the boundary.  Called every
        serving tick (``ServingEngine.tick``); a no-op with the default
        (None) watermarks.

        * **device side** — evicts LRU-leaf prefix-cache pages
          (``PrefixCacheManager.evict``), which stages each page host-side
          first via the demoter hook when ``demote_prefix`` is on: cold
          chains leave the arena but stay warm-on-host.  Pages pinned by
          live sequences are never touched (evict's refcount rule), so the
          sweep may legitimately fall short of the low watermark.
        * **host side** — drops the host tier's LRU-coldest entries
          (sequence snapshots and prefix pages alike, one LRU); a dropped
          parked snapshot degrades that resume to recompute (the ladder's
          never-wrong fallback) and a dropped prefix page just loses
          warmth.

        Returns ``{"device_demoted": pages, "host_dropped": pages}``."""
        cfg = self.config
        out = {"device_demoted": 0, "host_dropped": 0}
        if cfg.device_watermark_hi is not None:
            alloc = self.engine.kv.allocator
            usable = alloc.num_pages - 1          # page 0 is the null page
            used = usable - alloc.free_pages
            if usable > 0 and used / usable >= cfg.device_watermark_hi:
                # free down to the low watermark: target_used = lo * usable
                excess = used - int(cfg.device_watermark_lo * usable)
                pc = self.engine.kv.prefix_cache
                if pc is not None and excess > 0:
                    freed = pc.evict(excess)
                    out["device_demoted"] = freed
                    self.stats["watermark_demotions"] += freed
        if cfg.host_watermark_hi is not None:
            cap = self.host.capacity_pages
            if self.host.pages_used / cap >= cfg.host_watermark_hi:
                target = int(cfg.host_watermark_lo * cap)
                while self.host.pages_used > target:
                    victim = next(iter(self.host._lru), None)
                    if victim is None:
                        break
                    dropped = self.host._lru[victim]
                    self.host._drop(victim)   # coldest-first: LRU head
                    out["host_dropped"] += dropped
                self.stats["watermark_host_drops"] += out["host_dropped"]
        if out["device_demoted"] or out["host_dropped"]:
            self._count("kv/watermark_demote",
                        out["device_demoted"] + out["host_dropped"])
        return out

    def _demote_prefix_page(self, digest: int, page_id: int, tokens: tuple,
                            parent: Optional[int]) -> None:
        """``PrefixCacheManager.evict``'s demoter hook, invoked BEFORE the
        page is freed: stage the evicted chain page host-side so the prefix
        stays warm-on-host.  Best-effort: any degradable failure just
        loses the warmth (the chain goes cold, exactly as without a tier);
        ``InjectedCrash``/``DeviceLossError`` propagate."""
        arena = self.engine.cache
        if not hasattr(arena, "shape") or len(arena.shape) != 6:
            return
        try:
            _fi.check("kv.demote")   # same chaos site as sequence demotion
            block = self.engine.kv.export_pages(arena, [page_id])
        except _FATAL:
            raise
        except OSError as e:
            self.stats["demote_faults"] += 1
            logger.warning(f"kvtier: prefix demotion dropped ({e})")
            return
        ent = _HostPrefixPage(
            tokens=tuple(tokens), parent=parent, block=block,
            crc=zlib.crc32(np.ascontiguousarray(block).tobytes()),
            shape=tuple(block.shape), dtype=str(block.dtype))
        if self.host.put_prefix(digest, ent):
            self.stats["prefix_demotions"] += 1
            self._count("kv/demote")
            self._notify("host_publish", digest)

    # ----------------------------------------------------------- promotion

    def prefetch(self, uid: int, n_pages: int, now: float) -> None:
        """Issue the promote transfer for ``uid`` ahead of its admission
        (at resume/requeue time).  Double-buffered: at most
        ``prefetch_depth`` transfers overlap; a later issue queues behind
        the oldest in-flight slot.  Idempotent per uid — a re-issue keeps
        the earlier (better) window."""
        if uid in self._prefetch or n_pages <= 0:
            return
        transfer = n_pages * self.config.h2d_page_s
        busy = sorted(t for t in self._slots if t > now)
        self._slots = busy
        depth = max(1, self.config.prefetch_depth)
        start = now if len(busy) < depth else busy[len(busy) - depth]
        t_ready = start + transfer
        if transfer > 0:
            self._slots.append(t_ready)
        self._prefetch[uid] = (start, t_ready, transfer)

    def _settle_transfer(self, issued, n_pages: int, now: float):
        """Settle a promote transfer at admission: ``(stall_s, window)``
        where ``stall_s`` is the non-hidden remainder the admission must
        wait out and ``window`` the ``(t_start, t_ready)`` interval for
        span attribution (None when the transfer is free).  ``issued`` is
        the prefetch record, or None for a direct (unprefetched) claim —
        then the whole transfer stalls."""
        transfer = n_pages * self.config.h2d_page_s
        if transfer <= 0:
            return 0.0, None
        if issued is None:
            start, t_ready = now, now + transfer
            self._slots.append(t_ready)
        else:
            start, t_ready, transfer = issued
        stall = max(0.0, t_ready - now)
        self.stats["transfer_s"] += transfer
        self.stats["hidden_s"] += max(0.0, transfer - stall)
        return stall, (start, t_ready)

    def claim(self, uid: int, tokens, now: float):
        """Resolve a parked request's :class:`HostKVHandle` at admission:
        fire the ``kv.promote`` chaos site, take the host snapshot, and
        settle the prefetch window.  Returns ``(snapshot, stall_s,
        window)``; snapshot None on any degradable failure (entry
        LRU-evicted, token drift, transient fault) — the caller falls back
        to recompute.  Integrity is NOT checked here: ``import_snapshot``
        verifies every chunk crc before any scatter, so a torn host page
        is rejected there and the same fallback runs."""
        issued = self._prefetch.pop(uid, None)
        try:
            _fi.check("kv.promote")  # chaos site: failed h2d promotion
        except _FATAL:
            raise
        except OSError as e:
            self.host.discard_seq(uid)
            self.stats["promote_faults"] += 1
            logger.warning(f"kvtier: promotion of uid={uid} failed ({e}); "
                           "recompute-on-resume")
            return None, 0.0, None
        snap = self.host.take_seq(uid)
        if snap is None:
            self.stats["promote_fallbacks"] += 1
            return None, 0.0, None
        if list(snap.tokens) != [int(t) for t in tokens]:
            # the request's history moved past the parked snapshot (stale
            # entry from an earlier park): recompute owns it
            self.stats["promote_fallbacks"] += 1
            return None, 0.0, None
        stall, window = self._settle_transfer(issued, snap.n_pages, now)
        self.stats["promotions"] += 1
        self._count("kv/promote")
        return snap, stall, window

    # ---------------------------------------------------- prefix promotion

    def host_prefix_depth(self, tokens, start_depth: int = 0) -> int:
        """How many chain pages of ``tokens`` from ``start_depth`` onward
        the HOST tier holds (token-verified contiguous run) — the
        warm-on-host half of a tiered warmth answer."""
        return len(self._host_chain(tokens, start_depth))

    def _host_chain(self, tokens, start_depth: int,
                    max_depth: Optional[int] = None):
        P = self.engine.kv.page_size
        chain = prefix_chain_hashes(tokens, P)
        hi = len(chain) if max_depth is None else min(len(chain), max_depth)
        out = []
        for i in range(start_depth, hi):
            ent = self.host.get_prefix(chain[i])
            if ent is None or ent.tokens != tuple(tokens[i * P:(i + 1) * P]):
                break
            out.append((chain[i], ent))
        return out

    def host_prefix_blocks(self, tokens, start_depth: int,
                           max_depth: Optional[int] = None) -> List[np.ndarray]:
        """Crc-verified staged blocks continuing ``tokens``'s chain from
        ``start_depth`` — the donor-side source for saturated-warm prefix
        exports that must not touch the device arena.  A corrupt entry is
        dropped and the run stops there (shorter warmth, never wrong KV)."""
        blocks = []
        for digest, ent in self._host_chain(tokens, start_depth, max_depth):
            block = self.host.prefix_block(ent)
            if zlib.crc32(np.ascontiguousarray(block).tobytes()) != ent.crc:
                logger.warning("kvtier: corrupt host prefix page rejected "
                               "by crc before scatter")
                self.host.drop_prefix(digest)
                break
            blocks.append(block)
        return blocks

    def promote_prefix(self, tokens, now: float):
        """Fill the device prefix cache's missing chain tail for
        ``tokens`` from host pages (allocate → crc-checked scatter →
        ``adopt``, the import_prefix contract) so the subsequent
        ``match()`` attaches them instead of recomputing their KV.
        Returns ``(pages_promoted, stall_s, window)``.  Consumed host
        entries are dropped — the device copy is the warm one now.  Every
        failure degrades: 0 pages promoted, prefill recomputes."""
        kv = self.engine.kv
        pc = kv.prefix_cache
        arena = self.engine.cache
        if pc is None or not hasattr(arena, "shape") or len(arena.shape) != 6:
            return 0, 0.0, None
        # same usable cap as match(): the engine must still compute >= 1
        # prompt token, so a page covering the final token is useless
        max_depth = max(0, (len(tokens) - 1) // kv.page_size)
        have = pc.held_depth(tokens)
        run = self._host_chain(tokens, have, max_depth)
        if not run:
            return 0, 0.0, None
        try:
            _fi.check("kv.promote")  # chaos site: failed h2d promotion
        except _FATAL:
            raise
        except OSError as e:
            self.stats["promote_faults"] += 1
            logger.warning(f"kvtier: prefix promotion failed ({e}); "
                           "prefill recomputes")
            return 0, 0.0, None
        blocks = []
        for digest, ent in run:
            block = self.host.prefix_block(ent)
            if zlib.crc32(np.ascontiguousarray(block).tobytes()) != ent.crc:
                logger.warning("kvtier: corrupt host prefix page rejected "
                               "by crc before scatter")
                self.host.drop_prefix(digest)
                break
            blocks.append((digest, block))
        if not blocks:
            return 0, 0.0, None
        n = len(blocks)
        if n > kv.allocator.free_pages:
            pc.evict(n - kv.allocator.free_pages)
            if pc.held_depth(tokens) != have or n > kv.allocator.free_pages:
                # the sweep ate this very chain (or came up short): the
                # host copies survive for a later attempt
                return 0, 0.0, None
        pages = kv.allocator.allocate(n)
        try:
            stacked = np.concatenate([b for _, b in blocks], axis=1)
            self.engine.cache = kv.import_pages(self.engine.cache, pages,
                                                np.ascontiguousarray(stacked))
        except BaseException:
            kv.allocator.free(pages)
            raise
        pc.adopt(list(tokens[:(have + n) * kv.page_size]), have, pages)
        for digest, _ in blocks:
            self.host.drop_prefix(digest)   # device-warm now; emits host_evict
        stall, window = self._settle_transfer(None, n, now)
        self.stats["prefix_promotions"] += n
        self._count("kv/promote")
        return n, stall, window
