"""Tiered paged KV: host-offload tier with park/resume, demotion-first
preemption, and prefetch-hidden promotion (docs/SERVING.md "Tiered KV")."""

from .tier import HostKVHandle, HostKVTier, TierConfig, TieredKVManager

__all__ = ["TierConfig", "HostKVHandle", "HostKVTier", "TieredKVManager"]
