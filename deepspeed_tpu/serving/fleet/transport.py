"""Simulated control-plane fabric: typed, versioned, sequence-numbered
messages between the fleet router and its replicas, with injectable loss,
duplication, reordering, bounded delay and named partition windows — all
driven by the shared ``VirtualClock`` so every delivery schedule is
bit-reproducible (docs/SERVING.md "Control-plane transport").

Until r16 every fleet control flow — health observation, ``load_stats()``
routing signals, prefix-directory publishes, migration chunk pumps,
autoscaler inputs — was a perfect, instantaneous in-process call.  A real
multi-host fleet gets none of that: its control plane is datagrams that
drop, duplicate, arrive late or out of order, and sometimes cannot cross
a network partition at all.  This module is the deterministic stand-in
for that fabric, and the rest of ``serving/fleet`` re-homes its control
flows onto it:

* **heartbeats + leases** — replicas heartbeat their health state and
  ``load_stats()`` each round; the router's
  :class:`~.health.FleetHealthView` turns silence into SUSPECT (no new
  dispatches) and an expired lease into a fleet-declared death
  (``Router.on_lease_expired``: displaced work is re-dispatched, the
  replica's dispatch epoch is bumped, and a surviving "zombie" replica is
  FENCED on its first post-partition heartbeat — its late completions are
  discarded, so no request is ever served twice);
* **sequence-numbered state sync** — prefix-directory publishes carry a
  per-replica ``(rid, seqno)``; a gap triggers ``prefix/publish_gap`` and
  a targeted full-digest resync instead of silent absorption;
* **ack/retry chunk delivery** — migration chunks flow stop-and-wait with
  cumulative acks and idempotent (index-checked) import, so loss costs
  retransmits, never torn snapshots;
* **lifecycle commands** (r21) — autoscaler recover/drain/park/
  role-change and migration completion ride typed, seq-numbered,
  epoch-fenced ``lifecycle_cmd`` messages with the same stop-and-wait
  ack/retry discipline as migration chunks; the replica side dedups by
  command seq and rejects commands stamped with a pre-fencing epoch, so
  a partitioned or zombie replica can never act on — or double-apply —
  a stale command (``Router._apply_lifecycle``).

Message taxonomy (``kind``):

=================  =========================  ==============================
kind               direction                  payload
=================  =========================  ==============================
``heartbeat``      replica -> router          local health state, load_stats
``dir_publish``    replica -> router          prefix digest publish/retract
``dir_resync_req`` router -> replica          request a full-digest snapshot
``dir_resync``     replica -> router          digests + publish-seq barrier
``fence``          router -> replica          dispatch epoch to fence
``fence_ack``      replica -> router          epoch echo + cancel counts
``mig_chunk``      source replica -> router   KV chunk (idx, crc, last flag)
``mig_ack``        router -> source replica   cumulative chunk ack
``lifecycle_cmd``  router -> replica          op + cmd seq + dispatch epoch
``lifecycle_ack``  replica -> router          cmd seq + epoch echo + status
=================  =========================  ==============================

Faults are drawn per message in SEND order from one seeded
``random.Random``, so the same workload + fault config + partition
schedule replays the same delivery sequence byte-for-byte on every run
and machine.  The ``transport.send`` / ``transport.deliver`` injection
sites (docs/RESILIENCE.md) additionally let the chaos harness drop
specific messages (``os_error``) or kill the driver mid-flight
(``crash``) at deterministic hit counts.

Correctness stance, as everywhere in this repo: the transport may make
the fleet SLOWER (stale routing, retransmits, lease waits) but never
WRONG — final outputs stay byte-identical to the unperturbed golden run
under every schedule, which is exactly what
``tests/unit/resilience/test_transport_chaos.py`` pins.
"""

import dataclasses
import random
from typing import Dict, Iterable, List, Optional, Tuple, Union

from ...resilience import fault_injection as _fi

#: wire-format version stamped on every message; a receiver that sees a
#: different major version must resync, not guess (single-version today)
MESSAGE_VERSION = 1

#: the closed message-kind vocabulary; ``send`` rejects unknown kinds so a
#: typo'd control flow fails loudly instead of silently never delivering
MESSAGE_KINDS = frozenset({
    "heartbeat", "dir_publish", "dir_resync_req", "dir_resync",
    "fence", "fence_ack", "mig_chunk", "mig_ack",
    "lifecycle_cmd", "lifecycle_ack",
})

#: the control-plane endpoint name of the router; replicas are their rids
ROUTER = "router"

Endpoint = Union[str, int]


@dataclasses.dataclass(frozen=True)
class Message:
    """One typed, versioned, sequence-numbered control-plane datagram."""
    kind: str
    src: Endpoint
    dst: Endpoint
    seq: int                 # per-(src, kind-stream) sequence number
    send_ts: float
    payload: dict
    version: int = MESSAGE_VERSION
    #: transport-global monotonic message id — the CAUSAL link between a
    #: send and its delivery(ies) in the flight recorder: the ``ctrl/*``
    #: span a delivery materializes carries this id, and a duplicated
    #: message's two deliveries share it (dup visible per link)
    mid: int = 0


@dataclasses.dataclass(frozen=True)
class LinkFaults:
    """Per-link fault model.  All probabilities are drawn per message in
    send order from the transport's one seeded RNG."""
    loss_p: float = 0.0        # message silently dropped
    dup_p: float = 0.0         # a second copy is delivered late
    reorder_p: float = 0.0     # message delayed past its successors
    delay: float = 0.0         # base one-way delivery delay (seconds)
    reorder_delay: float = 1.0  # extra delay for reordered/duplicated copies

    def __post_init__(self):
        for name in ("loss_p", "dup_p", "reorder_p"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name}={p} not a probability")
        if self.delay < 0 or self.reorder_delay < 0:
            raise ValueError(f"negative delay ({self.delay}, {self.reorder_delay})")


@dataclasses.dataclass(frozen=True)
class PartitionWindow:
    """A NAMED partition: the listed endpoint pairs cannot exchange
    messages (either direction) while ``t0 <= ts < t1``.  Severance is
    checked at BOTH ends of a message's flight — at send time and again
    at delivery time — so a partition also eats datagrams already in the
    air when it starts (the pessimistic model; a fabric that queued them
    would only be kinder)."""
    name: str
    t0: float
    t1: float
    pairs: Tuple[Tuple[Endpoint, Endpoint], ...]

    def __post_init__(self):
        if not self.t1 > self.t0:
            raise ValueError(f"partition '{self.name}' window empty "
                             f"({self.t0}, {self.t1})")
        object.__setattr__(self, "pairs",
                           tuple((a, b) for a, b in self.pairs))

    def severs(self, a: Endpoint, b: Endpoint, ts: float) -> bool:
        if not self.t0 <= ts < self.t1:
            return False
        return any({a, b} == {x, y} for x, y in self.pairs)


class ControlTransport:
    """The deterministic fabric every fleet control message crosses.

    ``send`` schedules delivery (or drops, duplicates, delays per the
    seeded fault model and partition schedule); ``deliver(now)`` returns
    every message whose delivery time has come, in deterministic
    ``(deliver_ts, enqueue order)`` order.  With the default
    ``LinkFaults()`` and no partitions the transport is PERFECT (zero
    delay, zero loss): behavior is observationally identical to the
    pre-transport in-process fleet, one poll-round of latency aside.
    """

    def __init__(self, clock, faults: LinkFaults = None, seed: int = 0,
                 partitions: Iterable[PartitionWindow] = (),
                 link_faults: Optional[Dict[frozenset, LinkFaults]] = None,
                 metrics=None, recorder=None):
        self.clock = clock
        self.faults = faults or LinkFaults()
        #: per-link overrides keyed by ``frozenset({a, b})``
        self.link_faults = dict(link_faults or {})
        self.partitions: List[PartitionWindow] = list(partitions)
        self.metrics = metrics
        #: optional flight recorder (telemetry/flight_recorder.py): every
        #: DELIVERED message becomes a ``ctrl/<kind>`` span [send_ts,
        #: deliver_ts] on its link's track (the send→deliver causal pair,
        #: dup deliveries sharing the message's ``mid``), every message
        #: the fabric ate a ``ctrl/drop`` instant with its cause — the
        #: per-link drop/dup/retransmit visibility the recorder exists for
        self.recorder = recorder
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self._eid = 0                        # total enqueue order (determinism)
        self._mid = 0                        # causal message ids (recorder)
        #: in-flight: (deliver_ts, eid, Message) — sorted at deliver time
        self._in_flight: List[Tuple[float, int, Message]] = []
        self.stats = {
            "sent": 0, "delivered": 0, "dropped": 0, "partition_dropped": 0,
            "duplicated": 0, "reordered": 0, "delayed": 0, "send_faults": 0,
            "deliver_faults": 0, "retransmits": 0,
        }
        #: per-link health accounting for the adaptive-lease-sizing signal
        #: (ROADMAP): ``loss_ewma`` folds every message's RESOLVED fate
        #: (1 = eaten — at send by loss/fault/partition, or at deliver by
        #: a deliver fault / a partition that opened mid-flight; 0 =
        #: delivered) with alpha 0.2 — keyed by frozenset({a, b})
        self._link_health: Dict[frozenset, dict] = {}

    # ------------------------------------------------------------- topology

    def add_partition(self, window: PartitionWindow) -> None:
        self.partitions.append(window)

    def connected(self, a: Endpoint, b: Endpoint, ts: float) -> bool:
        """Is the (a, b) link traversable at ``ts`` (partition schedule
        only — random loss is per-message, not a link state)?"""
        return not any(p.severs(a, b, ts) for p in self.partitions)

    def active_partitions(self, ts: float) -> List[str]:
        return [p.name for p in self.partitions if p.t0 <= ts < p.t1]

    def _link(self, a: Endpoint, b: Endpoint) -> LinkFaults:
        return self.link_faults.get(frozenset((a, b)), self.faults)

    # ----------------------------------------------------------------- send

    def _count(self, name: str) -> None:
        self.stats[name] += 1
        if self.metrics is not None:
            self.metrics.counter(f"transport/{name}").inc()

    def note_retransmit(self) -> None:
        """A reliable stream (fence retry, chunk stop-and-wait, resync
        re-request) re-sent a message the receiver never acked."""
        self._count("retransmits")

    def _track(self, src: Endpoint, dst: Endpoint) -> str:
        return f"ctrl/link/{src}-{dst}"

    def _note_link(self, src: Endpoint, dst: Endpoint, eaten: bool) -> None:
        """Fold one RESOLVED message fate into the link's health.  Called
        exactly once per message at the point its fate is known — a
        send-time drop, a deliver-time drop, or a delivery (a duplicated
        message's extra copy resolves separately: the link genuinely
        carried both) — so a link whose sends depart fine but whose
        deliveries all die still reads as lossy."""
        h = self._link_health.get(frozenset((src, dst)))
        if h is None:
            h = self._link_health[frozenset((src, dst))] = {
                "resolved": 0, "eaten": 0, "loss_ewma": 0.0}
        h["resolved"] += 1
        if eaten:
            h["eaten"] += 1
        h["loss_ewma"] = 0.8 * h["loss_ewma"] + 0.2 * (1.0 if eaten else 0.0)

    def link_loss_ewma(self, a: Endpoint, b: Endpoint) -> float:
        """Observed loss EWMA of the (a, b) link — random loss, injected
        send/deliver faults and partition severance (at send OR opening
        mid-flight) folded together (what matters to a lease is whether
        messages GET THROUGH, not why they don't).  0.0 before any
        resolved traffic; messages still in flight have no fate yet.  The
        per-round ``transport/link_loss_ewma/<rid>`` gauge — ROADMAP's
        adaptive-lease-sizing input signal — reads this."""
        h = self._link_health.get(frozenset((a, b)))
        return 0.0 if h is None else h["loss_ewma"]

    def link_health(self) -> Dict[str, dict]:
        """Deterministically-keyed per-link health table (summary surface)."""
        out = {}
        for key in sorted(self._link_health, key=lambda k: sorted(map(str, k))):
            a, b = sorted(map(str, key))
            h = self._link_health[key]
            out[f"{a}-{b}"] = {"resolved": h["resolved"], "eaten": h["eaten"],
                               "loss_ewma": round(h["loss_ewma"], 9)}
        return out

    def send(self, kind: str, src: Endpoint, dst: Endpoint, payload: dict,
             seq: int = 0) -> Optional[Message]:
        """Schedule one message.  Returns the Message when it was put in
        flight, None when the fabric ate it (loss, partition, injected
        send fault) — senders that need delivery retry on a timer; the
        fire-and-forget streams (heartbeats, publishes) rely on leases
        and seq-gap resync instead."""
        if kind not in MESSAGE_KINDS:
            raise ValueError(f"unknown message kind '{kind}'; one of "
                             f"{sorted(MESSAGE_KINDS)}")
        now = self.clock.now()
        self._count("sent")
        self._mid += 1
        mid = self._mid
        try:
            # chaos site: the send edge of every control message
            _fi.check("transport.send")
        except _fi.InjectedCrash:
            raise  # simulated death of THIS driver process
        except OSError:
            # injected send fault: the datagram never left the host
            self._count("send_faults")
            self._count("dropped")
            self._note_link(src, dst, eaten=True)
            self._record_drop(kind, src, dst, seq, mid, now, "send_fault")
            return None
        msg = Message(kind=kind, src=src, dst=dst, seq=int(seq),
                      send_ts=now, payload=payload, mid=mid)
        if not self.connected(src, dst, now):
            self._count("partition_dropped")
            self._note_link(src, dst, eaten=True)
            self._record_drop(kind, src, dst, seq, mid, now, "partition")
            return None
        link = self._link(src, dst)
        # ONE rng, consumed in send order: loss, reorder, dup — always all
        # three draws, so a fired fault never shifts its successors' draws
        lost = self._rng.random() < link.loss_p
        reordered = self._rng.random() < link.reorder_p
        duped = self._rng.random() < link.dup_p
        if lost:
            self._count("dropped")
            self._note_link(src, dst, eaten=True)
            self._record_drop(kind, src, dst, seq, mid, now, "loss")
            return None
        delay = link.delay
        if reordered:
            delay += link.reorder_delay
            self._count("reordered")
        if delay > 0:
            self._count("delayed")
        self._eid += 1
        self._in_flight.append((now + delay, self._eid, msg))
        if duped:
            self._count("duplicated")
            self._eid += 1
            self._in_flight.append((now + delay + link.reorder_delay,
                                    self._eid, msg))
        return msg

    # -------------------------------------------------------------- deliver

    def deliver(self, now: Optional[float] = None) -> List[Message]:
        """Pop every message due by ``now`` in (deliver_ts, enqueue) order.
        A message whose link is severed at its DELIVERY instant is eaten
        by the partition (it was in the air when the cut landed)."""
        now = self.clock.now() if now is None else now
        due = [e for e in self._in_flight if e[0] <= now]
        if not due:
            return []
        due.sort(key=lambda e: (e[0], e[1]))
        self._in_flight = [e for e in self._in_flight if e[0] > now]
        out: List[Message] = []
        for deliver_ts, _eid, msg in due:
            try:
                # chaos site: the delivery edge (receiver-side I/O)
                _fi.check("transport.deliver")
            except _fi.InjectedCrash:
                raise  # simulated death of THIS driver process
            except OSError:
                self._count("deliver_faults")
                self._count("dropped")
                self._note_link(msg.src, msg.dst, eaten=True)
                self._record_drop(msg.kind, msg.src, msg.dst, msg.seq,
                                  msg.mid, deliver_ts, "deliver_fault")
                continue
            if not self.connected(msg.src, msg.dst, deliver_ts):
                self._count("partition_dropped")
                self._note_link(msg.src, msg.dst, eaten=True)
                self._record_drop(msg.kind, msg.src, msg.dst, msg.seq,
                                  msg.mid, deliver_ts, "partition")
                continue
            self._count("delivered")
            self._note_link(msg.src, msg.dst, eaten=False)
            if self.recorder is not None:
                # the causal send→deliver pair: one span per delivery,
                # [send_ts, deliver_ts] on the link's track; duplicated
                # copies share the mid (dups visible), retransmits show as
                # distinct mids of the same (kind, seq)
                self.recorder.span(f"ctrl/{msg.kind}",
                                   self._track(msg.src, msg.dst),
                                   msg.send_ts, deliver_ts,
                                   attrs={"src": str(msg.src),
                                          "dst": str(msg.dst),
                                          "seq": msg.seq, "mid": msg.mid})
            out.append(msg)
        return out

    def _record_drop(self, kind: str, src: Endpoint, dst: Endpoint, seq: int,
                     mid: int, ts: float, cause: str) -> None:
        if self.recorder is not None:
            self.recorder.instant("ctrl/drop", self._track(src, dst), ts,
                                  attrs={"kind": kind, "src": str(src),
                                         "dst": str(dst), "seq": int(seq),
                                         "mid": mid, "cause": cause})

    # ------------------------------------------------------------- schedule

    def next_wake(self, now: float) -> List[float]:
        """Instants at which the fabric's state can change: pending
        delivery times (a message already DUE reports ``now`` — the next
        poll round will deliver it, so a stalled simulator must take a
        zero-width step, not jump past it) and partition window boundaries
        — the idle-jump input (a stalled fleet must wake when a partition
        heals, not spin or die)."""
        out = [max(ts, now) for ts, _, _ in self._in_flight]
        for p in self.partitions:
            for b in (p.t0, p.t1):
                if b > now:
                    out.append(b)
        return out

    @property
    def in_flight(self) -> int:
        return len(self._in_flight)

    def summary(self) -> dict:
        return {**self.stats, "in_flight": len(self._in_flight),
                "partitions": [p.name for p in self.partitions],
                "links": self.link_health(),
                "seed": self.seed}
