"""Overload control plane: SLA autoscaler + graceful-degradation ladder.

The fleet so far was a fixed N with scripted kills: under a flash crowd
it could only reject at the front door.  This module closes the loop the
DeepSpeed blueprint's elasticity layer (``DSElasticAgent``) implies for
serving — a deterministic policy loop that reads the signals the stack
already exposes (per-replica ``load_stats()``, fleet queue depth, a TTFT
EWMA folded from completions) and acts through the EXISTING replica
lifecycle, so no new failure modes are invented:

* **scale up** — a ``recover`` lifecycle command on a parked (DEAD)
  replica: the fresh engine warms through the RECOVERING probe path
  before it takes dispatches, exactly like a replacement host joining;
* **scale down** — a ``drain`` command then, only once the replica is
  IDLE, a ``park``.  In-flight work is NEVER killed by a scale decision;
  a device loss *during* the drain fails the victims over through the
  ordinary recompute-on-resume path with byte-identical outputs
  (chaos-tested).
* **hysteresis + cooldown** — separate up/down thresholds, a consecutive
  low-streak requirement, and per-direction cooldowns, so the fleet does
  not flap between sizes on a noisy boundary.
* **predictive scale-up** (``predictive=True``) — provision from the
  demand FORECAST: the router's arrival-rate EWMA projected along its
  slope to the replica warm-up horizon, plus premium-tenant SLO
  fast-burn; the reactive thresholds above stay armed as the safety net.
* **role-aware rebalancing** (``role_aware=True``) — when one serving
  phase's pressure dwarfs the other's, drain one replica of the
  over-provisioned phase and re-role it toward the starved one
  (MIXED <-> PREFILL/DECODE), through the same drain-gated path.

Every mutation flows through ``Router.lifecycle_command``: the direct
pool calls without a control transport (byte-identical to the pre-r21
autoscaler), typed + seq-numbered + epoch-fenced + retried-until-acked
``lifecycle_cmd`` messages under one — a partitioned or fenced replica
can never act on (or double-apply) a stale scale decision.

Alongside it the :class:`OverloadController` runs the graceful-
degradation ladder: when shedding capacity is not enough, the fleet
BROWNS OUT in explicit, auditable rungs rather than falling over —

    rung 1  cap max_new_tokens for best-effort tenants
    rung 2  disable speculative decoding (greedy parity: outputs identical)
    rung 3  pause starting KV migrations / prefix imports
    rung 4  shed best-effort admissions with a retry-after hint

and steps back DOWN the same rungs symmetrically as pressure clears.
Every move emits a ``fleet/overload_step_up``/``_step_down`` event and is
recorded with per-rung occupancy time, so a bench can assert that every
rung entered was also exited.

Determinism: decisions are pure functions of clock time and fleet state,
probed through the ``autoscaler.decide`` fault-injection site — the same
flash crowd replays the same decision sequence byte-for-byte on every
run and machine (the ``BENCH_ROUTER.json`` ``autoscale`` receipt).
"""

import dataclasses
from typing import Dict, List, Optional, Tuple

from ...resilience import fault_injection as _fi
from ...utils.logging import logger
from .health import ReplicaState
from .pool import ReplicaRole
from .tenancy import TenantSpec

# ---------------------------------------------------------------- overload


#: the graceful-degradation ladder, rung 0 = normal service.  Order is the
#: escalation order; stepping down retraces it symmetrically.
RUNGS = ("normal", "cap_tokens", "no_spec", "pause_migration",
         "shed_best_effort")


@dataclasses.dataclass(frozen=True)
class OverloadConfig:
    #: pressure at/above which the ladder steps UP one rung
    hi: float = 1.0
    #: pressure at/below which it steps back DOWN (hysteresis band)
    lo: float = 0.6
    #: min clock time between rung moves (no flapping)
    cooldown: float = 3.0
    #: rung >= 1: max_new_tokens cap applied to best-effort admissions
    token_cap: int = 8
    #: retry-after hint stamped on rung-4 shed rejections
    retry_after: float = 8.0

    def __post_init__(self):
        if not self.lo < self.hi:
            raise ValueError(f"overload hysteresis needs lo < hi "
                             f"(got lo={self.lo}, hi={self.hi})")
        if self.token_cap < 1:
            raise ValueError(f"token_cap must be >= 1, got {self.token_cap}")


class OverloadController:
    """Explicit brownout ladder; see module docstring for the rungs."""

    def __init__(self, config: OverloadConfig = None, emit=None,
                 recorder=None):
        self.config = config or OverloadConfig()
        self._emit = emit            # emit(name, value) or None
        #: optional flight recorder: rung occupancy becomes a first-class
        #: interval track (``ctrl/overload/<rung>`` on track
        #: ``ctrl/overload``) — how long the fleet sat on each brownout
        #: rung is readable straight off the crash dump
        self.recorder = recorder
        self.rung = 0
        self.shed_count = 0
        #: (ts, "up"/"down", new_rung, pressure) per move — the audit log
        self.moves: List[Tuple[float, str, int, float]] = []
        self.entered: Dict[int, int] = {}    # rung -> times entered
        self.exited: Dict[int, int] = {}     # rung -> times exited
        self.occupancy: Dict[int, float] = {r: 0.0 for r in range(len(RUNGS))}
        self._last_move: Optional[float] = None
        self._last_ts: Optional[float] = None

    def bind(self, emit) -> None:
        """Attach the event sink (the router's monitor emitter)."""
        self._emit = emit

    # ------------------------------------------------------------- queries

    @property
    def token_cap_active(self) -> bool:
        return self.rung >= 1

    @property
    def spec_disabled(self) -> bool:
        return self.rung >= 2

    @property
    def migrations_paused(self) -> bool:
        return self.rung >= 3

    def shed(self, spec: TenantSpec) -> bool:
        """Should this tenant's admission be shed right now?  Only
        best-effort tenants are ever shed — premium/standard traffic rides
        the ladder's milder rungs and the autoscaler's added capacity."""
        return self.rung >= 4 and spec.best_effort

    # ------------------------------------------------------------- updates

    def update(self, now: float, pressure: float) -> None:
        """Fold elapsed occupancy and move at most ONE rung, respecting
        the hysteresis band and cooldown.  ``pressure`` is the control
        plane's scalar overload signal (1.0 = at the SLO boundary)."""
        if self._last_ts is None and self.recorder is not None:
            # first observation: open the current (normal) rung's interval
            self._note_rung(now)
        if self._last_ts is not None and now > self._last_ts:
            self.occupancy[self.rung] += now - self._last_ts
        self._last_ts = now
        if self._last_move is not None and \
                now - self._last_move < self.config.cooldown:
            return
        if pressure >= self.config.hi and self.rung < len(RUNGS) - 1:
            self.rung += 1
            self.entered[self.rung] = self.entered.get(self.rung, 0) + 1
            self.moves.append((round(now, 9), "up", self.rung,
                               round(pressure, 9)))
            self._last_move = now
            self._note_rung(now, pressure)
            logger.warning(f"overload ladder UP -> rung {self.rung} "
                           f"({RUNGS[self.rung]}) at pressure {pressure:.3f}")
            if self._emit is not None:
                self._emit("fleet/overload_step_up", float(self.rung))
        elif pressure <= self.config.lo and self.rung > 0:
            self.exited[self.rung] = self.exited.get(self.rung, 0) + 1
            self.rung -= 1
            self.moves.append((round(now, 9), "down", self.rung,
                               round(pressure, 9)))
            self._last_move = now
            self._note_rung(now, pressure)
            logger.info(f"overload ladder DOWN -> rung {self.rung} "
                        f"({RUNGS[self.rung]}) at pressure {pressure:.3f}")
            if self._emit is not None:
                self._emit("fleet/overload_step_down", float(self.rung))

    def _note_rung(self, now: float, pressure: Optional[float] = None) -> None:
        if self.recorder is None:
            return
        attrs = {"rung": self.rung}
        if pressure is not None:
            attrs["pressure"] = round(pressure, 9)
        self.recorder.note_state("ctrl/overload",
                                 f"ctrl/overload/{RUNGS[self.rung]}", now,
                                 attrs=attrs)

    def record_shed(self) -> None:
        self.shed_count += 1

    def finalize(self, now: float) -> None:
        """Close the occupancy accounting at end of run."""
        if self._last_ts is not None and now > self._last_ts:
            self.occupancy[self.rung] += now - self._last_ts
        self._last_ts = now

    def summary(self) -> dict:
        """The auditable ladder record: every rung entered must also have
        been exited for ``balanced`` to hold (equivalently: final rung 0)."""
        balanced = self.rung == 0 and all(
            self.entered.get(r, 0) == self.exited.get(r, 0)
            for r in range(1, len(RUNGS)))
        return {
            "rung": self.rung,
            "rungs": list(RUNGS),
            "moves": [list(m) for m in self.moves],
            "entered": {RUNGS[r]: n for r, n in sorted(self.entered.items())},
            "exited": {RUNGS[r]: n for r, n in sorted(self.exited.items())},
            "occupancy": {RUNGS[r]: round(t, 6)
                          for r, t in sorted(self.occupancy.items()) if t > 0
                          or r == 0},
            "shed": self.shed_count,
            "balanced": balanced,
        }


# -------------------------------------------------------------- autoscaler


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    #: availability floor: the autoscaler recovers parked replicas to keep
    #: at least this many provisioned, load or no load
    min_replicas: int = 1
    #: provisioning ceiling (defaults to the pool size)
    max_replicas: Optional[int] = None
    #: the fleet TTFT budget the pressure signal is normalized against
    ttft_slo: float = 40.0
    #: TTFT-EWMA fraction of the SLO at/above which pressure reads 1.0
    up_frac: float = 0.8
    #: queued-requests-per-dispatchable-replica at which pressure reads 1.0
    queue_hi: float = 3.0
    #: scale DOWN only while outstanding-per-dispatchable stays at/below this
    queue_lo: float = 0.5
    #: consecutive low evaluations required before a scale-down drain starts
    down_streak: int = 3
    #: min time between scale-ups / between scale-downs (anti-flap)
    cooldown_up: float = 2.0
    cooldown_down: float = 8.0
    #: min time between decision evaluations
    decide_interval: float = 1.0
    #: TTFT EWMA smoothing (weight of each new completion)
    ewma_alpha: float = 0.3
    #: provision from the demand FORECAST — the router's arrival-rate
    #: EWMA projected along its slope to ``warmup_horizon``, plus
    #: premium-tenant SLO fast-burn — instead of waiting for queue/TTFT
    #: pressure to confirm the crowd already arrived (reactive thresholds
    #: stay armed underneath as the safety net)
    predictive: bool = False
    #: seconds a recovered replica needs before it takes dispatches — the
    #: horizon the demand forecast is projected to: capacity ordered NOW
    #: is only useful against the demand arriving THEN
    warmup_horizon: float = 4.0
    #: requests/second one dispatchable replica absorbs inside SLO — the
    #: capacity yardstick the forecast is compared against
    per_replica_rate: float = 1.0
    #: reassign replica roles (MIXED <-> PREFILL/DECODE) from phase
    #: imbalance, drain-gated so no in-flight work is lost
    role_aware: bool = False
    #: the starved phase's per-capable-replica pressure must exceed the
    #: other phase's by this factor before a role moves (hysteresis)
    role_imbalance: float = 1.5
    #: min time between role reassignments (a role change costs a drain
    #: plus a restart — it must not flap)
    role_cooldown: float = 8.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, got {self.min_replicas}")
        if not self.queue_lo < self.queue_hi:
            raise ValueError(f"autoscale hysteresis needs queue_lo < queue_hi "
                             f"(got {self.queue_lo}, {self.queue_hi})")
        if self.warmup_horizon < 0:
            raise ValueError(f"warmup_horizon must be >= 0, "
                             f"got {self.warmup_horizon}")
        if not self.per_replica_rate > 0:
            raise ValueError(f"per_replica_rate must be > 0, "
                             f"got {self.per_replica_rate}")
        if not self.role_imbalance > 1.0:
            raise ValueError(f"role_imbalance must be > 1.0 (a factor), "
                             f"got {self.role_imbalance}")


class Autoscaler:
    """Deterministic SLA autoscaler over one Router's ReplicaPool.

    Drive it once per fleet round (``FleetSimulator(router,
    autoscaler=...)`` does) — ``step(now)`` folds new completion TTFTs
    into the EWMA, advances any in-progress scale-down drain, updates the
    overload ladder, and evaluates at most one scale decision per
    ``decide_interval``.  Decisions land in :attr:`decisions` —
    ``(ts, action, rid, reason)`` — the byte-reproducibility receipt.
    """

    def __init__(self, router, config: AutoscaleConfig = None,
                 overload: Optional[OverloadController] = None):
        self.router = router
        self.pool = router.pool
        self.config = config or AutoscaleConfig()
        if self.config.max_replicas is not None and \
                self.config.max_replicas > len(self.pool.replicas):
            raise ValueError(
                f"max_replicas {self.config.max_replicas} exceeds the pool "
                f"size {len(self.pool.replicas)} — the pool is the ceiling")
        # the ladder is shared with the router (admission-time consults);
        # adopt the router's controller when one is already attached
        self.overload = overload if overload is not None \
            else getattr(router, "overload", None)
        if self.overload is not None and router.overload is None:
            router.overload = self.overload
        if self.overload is not None:
            self.overload.bind(self._emit_event)
        #: (ts, action, rid, reason) — byte-identical across same-seed runs
        self.decisions: List[Tuple[float, str, int, str]] = []
        self._ttft_ewma: Optional[float] = None
        self._folded = 0                 # index into router.ttft_log
        self._draining: Optional[int] = None
        self._drain_mode: Optional[str] = None   # "park" | "restart"
        self._last_eval: Optional[float] = None
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        self._last_role: Optional[float] = None
        self._low_streak = 0

    # ----------------------------------------------------------- telemetry

    def _emit_event(self, name: str, value: float) -> None:
        r = self.router
        r._emit([(name, value, r._next_event_step())])

    def _decide(self, now: float, action: str, rid: int, reason: str) -> None:
        self.decisions.append((round(now, 9), action, rid, reason))
        recorder = getattr(self.router, "recorder", None)
        if recorder is not None:
            # annotated instants on the dedicated control track: WHY the
            # fleet changed size is part of the flight-recorder story
            recorder.instant(f"ctrl/autoscale/{action}", "ctrl/autoscale",
                             now, attrs={"rid": rid, "reason": reason})
        logger.info(f"autoscaler: {action} replica {rid} at t={now:.3f} ({reason})")

    # ------------------------------------------------------------- signals

    @property
    def ttft_ewma(self) -> Optional[float]:
        return self._ttft_ewma

    def _fold_ttft(self) -> None:
        log = self.router.ttft_log
        a = self.config.ewma_alpha
        while self._folded < len(log):
            x = log[self._folded]
            self._folded += 1
            self._ttft_ewma = x if self._ttft_ewma is None \
                else (1 - a) * self._ttft_ewma + a * x

    def signals(self) -> dict:
        """Point-in-time control inputs — router queue depth, the TTFT
        EWMA, and per-replica load snapshots via
        ``Router.fleet_load_stats()``: a live probe without a control
        transport, LAST-KNOWN-GOOD heartbeat payloads with an ``age``
        annotation under one.  Stale inputs make the autoscaler react
        late (slower), never wrongly — and ``stats_age_max`` surfaces how
        stale its view was when it decided."""
        pool = self.pool
        stats = self.router.fleet_load_stats()
        dispatchable = self.router.dispatchable_rids()
        provisioned = [r for r in pool.rids
                       if pool.health.state(r) is not ReplicaState.DEAD]
        queued = self.router.queue_depth + \
            sum(s["queue_depth"] for s in stats.values())
        outstanding = self.router.outstanding
        free_pages = min((stats[r]["free_kv_pages"] for r in dispatchable
                          if r in stats), default=0)
        n_disp = max(1, len(dispatchable))
        ttft_pressure = 0.0
        if self._ttft_ewma is not None:
            ttft_pressure = self._ttft_ewma / max(
                1e-9, self.config.up_frac * self.config.ttft_slo)
        queue_pressure = (queued / n_disp) / max(1e-9, self.config.queue_hi)
        return {
            "dispatchable": dispatchable,
            "provisioned": provisioned,
            "queued": queued,
            "outstanding": outstanding,
            "free_kv_pages": free_pages,
            "ttft_ewma": self._ttft_ewma,
            "pressure": max(ttft_pressure, queue_pressure),
            # staleness receipt: the oldest load snapshot this decision
            # rests on (0.0 under perfect in-process observation)
            "stats_age_max": max((s.get("age", 0.0) for s in stats.values()),
                                 default=0.0),
        }

    # ---------------------------------------------------------------- step

    def step(self, now: Optional[float] = None) -> None:
        now = self.router.clock.now() if now is None else now
        self._fold_ttft()
        self._advance_drain(now)
        if self._last_eval is not None and \
                now - self._last_eval < self.config.decide_interval:
            return
        self._last_eval = now
        try:
            # chaos site: the control plane's probe of the fleet is where a
            # device loss on the replica it is draining/watching surfaces
            _fi.check("autoscaler.decide")
        except _fi.DeviceLossError as e:
            rid = self._draining
            if rid is None:
                live = [r for r in self.pool.rids
                        if self.pool.health.dispatchable(r)]
                rid = live[-1] if live else None
            if rid is None:
                raise
            self._draining, self._drain_mode = None, None
            self._decide(now, "device_loss", rid, str(e))
            self.router.on_replica_dead(rid, now, reason=str(e))
            return
        except OSError as e:
            # transient control-plane fault: skip this evaluation, the next
            # round re-reads the same deterministic signals
            logger.warning(f"autoscaler.decide transient fault: {e}")
            return
        sig = self.signals()
        if self.overload is not None:
            self.overload.update(now, sig["pressure"])
        self._evaluate(now, sig)

    def _advance_drain(self, now: float) -> None:
        """Progress an in-flight scale-down (or role change): park /
        restart / re-role the drained replica once — and only once — it
        is idle.  Runs every step, not just on decide ticks, so a drain
        never outlives its work.  Every mutation goes through
        ``Router.lifecycle_command`` — the direct pool calls without a
        transport, typed+retried+epoch-fenced commands under one."""
        rid = self._draining
        if rid is None:
            return
        if self.router.lifecycle_pending(rid, "drain"):
            # the drain COMMAND is still in flight on the fabric: the pool
            # state has not moved yet and must not read as an abort
            return
        state = self.pool.health.state(rid)
        if state is not ReplicaState.DRAINING:
            # killed (chaos) or otherwise transitioned out from under us:
            # the drain is moot, recovery/failover owns the replica now
            self._decide(now, "drain_aborted", rid, f"state {state.value}")
            self._draining, self._drain_mode = None, None
            return
        if not self.router.replica_idle(rid):
            return
        mode = self._drain_mode
        self._draining, self._drain_mode = None, None
        if mode == "restart":
            # scale-up arrived mid-drain: give the replica straight back
            # through the rolling-restart path instead of parking it
            self.router.lifecycle_command(rid, "restart", now=now)
            self._decide(now, "drain_cancelled", rid, "scale-up during drain")
            self._emit_event("fleet/scale_up", float(rid))
            self._last_up = now
            return
        if mode is not None and mode.startswith("role:"):
            role = mode.split(":", 1)[1]
            self.router.lifecycle_command(rid, "role_change",
                                          {"role": role}, now=now)
            self._decide(now, "role_change", rid,
                         f"drained idle; role -> {role}")
            return
        self.router.lifecycle_command(
            rid, "park", {"reason": "autoscale: scale-down (drained)"},
            now=now)
        self._decide(now, "down", rid, "drained idle; parked")
        self._emit_event("fleet/scale_down", float(rid))

    def _evaluate(self, now: float, sig: dict) -> None:
        cfg = self.config
        pool = self.pool
        n_prov = len(sig["provisioned"])
        n_disp = len(sig["dispatchable"])
        ceiling = cfg.max_replicas if cfg.max_replicas is not None \
            else len(pool.replicas)
        # a DEAD replica with a lifecycle command still in flight is
        # already being acted on — issuing a second mutation would race it
        dead = [r for r in pool.rids
                if pool.health.state(r) is ReplicaState.DEAD
                and not self.router.lifecycle_pending(r)]
        # availability floor first: below min_replicas we provision
        # unconditionally (no cooldown — this is repair, not reaction)
        if n_prov < cfg.min_replicas and dead:
            rid = dead[0]
            # via the router: a prefix directory pre-imports its hottest
            # chains while the replica is still RECOVERING (warm join)
            self.router.lifecycle_command(rid, "recover", now=now)
            self._decide(now, "up", rid, f"below min_replicas ({n_prov} < "
                         f"{cfg.min_replicas})")
            self._emit_event("fleet/scale_up", float(rid))
            self._last_up = now
            self._low_streak = 0
            return
        if cfg.role_aware and self._draining is None \
                and self._maybe_rebalance_roles(now, sig):
            return
        work = sig["queued"] + sig["outstanding"]
        kv_starved = sig["free_kv_pages"] == 0 and sig["queued"] > 0
        reactive_up = work > 0 and (sig["pressure"] >= 1.0 or kv_starved)
        predict_up, predict_reason, projected = False, "", 0.0
        if cfg.predictive:
            predict_up, predict_reason, projected = \
                self._predict_demand(now, sig)
        want_up = reactive_up or predict_up
        if want_up:
            self._low_streak = 0
            if self._last_up is not None and now - self._last_up < cfg.cooldown_up:
                return
            if self._draining is not None and self._drain_mode == "park":
                # cheapest capacity: cancel the in-flight scale-down — the
                # replica returns via restart the moment it is idle
                self._drain_mode = "restart"
                self._decide(now, "cancel_drain", self._draining,
                             "pressure while draining")
                self._last_up = now
                return
            if dead and n_prov < ceiling:
                rid = dead[0]
                self.router.lifecycle_command(rid, "recover", now=now)
                reason = predict_reason if (predict_up and not reactive_up) \
                    else (f"pressure {sig['pressure']:.3f}"
                          + (" (kv starved)" if kv_starved else ""))
                self._decide(now, "up", rid, reason)
                self._emit_event("fleet/scale_up", float(rid))
                self._last_up = now
            return
        low = sig["outstanding"] <= cfg.queue_lo * max(1, n_disp) \
            and sig["queued"] == 0
        if not low:
            self._low_streak = 0
            return
        if cfg.predictive and \
                projected > max(0, n_disp - 1) * cfg.per_replica_rate:
            # the queue is momentarily empty but the FORECAST still needs
            # today's capacity: do not start shrinking into a ramp
            self._low_streak = 0
            return
        self._low_streak += 1
        if self._low_streak < cfg.down_streak or self._draining is not None \
                or n_disp <= cfg.min_replicas:
            return
        if self._last_down is not None and now - self._last_down < cfg.cooldown_down:
            return
        rid = sig["dispatchable"][-1]
        self.router.lifecycle_command(rid, "drain", now=now)
        self._draining, self._drain_mode = rid, "park"
        self._decide(now, "drain", rid,
                     f"low occupancy x{self._low_streak}")
        self._emit_event("fleet/scale_drain", float(rid))
        self._last_down = now
        self._low_streak = 0

    def _predict_demand(self, now: float,
                        sig: dict) -> Tuple[bool, str, float]:
        """The predictive loop's forecast: project the arrival-rate EWMA
        along its slope to the warm-up horizon (capacity ordered NOW only
        serves demand arriving THEN) and compare against dispatchable
        capacity; independently, a premium tenant burning its SLO error
        budget at >= 1x on the fast window is demand the rate fold has
        not caught up to yet.  Returns ``(scale_up, reason, projected)``;
        the projected rate also guards scale-DOWN during a ramp."""
        cfg = self.config
        rate, slope = self.router.arrival_rate()
        projected = max(0.0, rate + slope * cfg.warmup_horizon)
        capacity = len(sig["dispatchable"]) * cfg.per_replica_rate
        if projected > capacity:
            return True, (f"projected {projected:.3f} req/s > capacity "
                          f"{capacity:.3f} at +{cfg.warmup_horizon:g}s"), \
                projected
        slo = getattr(self.router, "slo", None)
        if slo is not None:
            for name in self.router.tenants.names():
                spec = self.router.tenants.spec(name)
                if spec.ttft_slo is None or spec.best_effort:
                    continue
                fast, _slow = slo.burn_rates(name, now)
                if fast >= 1.0:
                    return True, (f"tenant {name!r} fast burn rate "
                                  f"{fast:.3f} >= 1.0"), projected
        return False, "", projected

    def _maybe_rebalance_roles(self, now: float, sig: dict) -> bool:
        """Phase-aware role reassignment (docs/SERVING.md "Disaggregated
        serving"): when one phase's per-capable-replica pressure dwarfs
        the other's by ``role_imbalance``, drain one replica of the
        over-provisioned phase and re-role it toward the starved one.
        The change rides the ordinary drain -> restart path, so no
        in-flight work is ever lost to a role decision.  Returns True
        when a role drain was started (the evaluation stops there: a
        role move IS this tick's decision)."""
        cfg = self.config
        if self._last_role is not None and \
                now - self._last_role < cfg.role_cooldown:
            return False
        disp = sig["dispatchable"]
        if len(disp) < 2:
            return False
        stats = self.router.fleet_load_stats()
        roles = {r: self.pool.replica(r).role for r in disp}
        prefill_caps = [r for r in disp if roles[r] is not ReplicaRole.DECODE]
        decode_caps = [r for r in disp if roles[r] is not ReplicaRole.PREFILL]
        prefill_demand = self.router.queue_depth + sum(
            stats[r]["queue_depth"] for r in prefill_caps if r in stats)
        decode_demand = sum(
            stats[r]["active"] for r in decode_caps if r in stats)
        p_press = prefill_demand / max(1, len(prefill_caps))
        d_press = decode_demand / max(1, len(decode_caps))
        rid, role = None, ""
        if p_press >= cfg.role_imbalance * max(d_press, 1e-9) and p_press > 0:
            # prefill starved: a pure-DECODE replica broadens to MIXED
            # (never below one decode-capable replica — migrated KV must
            # always have somewhere to land)
            pure_decode = [r for r in disp if roles[r] is ReplicaRole.DECODE]
            if pure_decode and len(decode_caps) > 1:
                rid, role = pure_decode[-1], "mixed"
        elif d_press >= cfg.role_imbalance * max(p_press, 1e-9) and d_press > 0:
            # decode starved: narrow a pure-PREFILL to MIXED first; with
            # no pure prefill left, specialize a MIXED toward DECODE —
            # only while another prefill-capable replica remains to admit
            pure_prefill = [r for r in disp if roles[r] is ReplicaRole.PREFILL]
            if pure_prefill and len(prefill_caps) > 1:
                rid, role = pure_prefill[-1], "mixed"
            else:
                mixed = [r for r in disp if roles[r] is ReplicaRole.MIXED]
                if mixed and len(prefill_caps) > 1:
                    rid, role = mixed[-1], "decode"
        if rid is None:
            return False
        self.router.lifecycle_command(rid, "drain", now=now)
        self._draining, self._drain_mode = rid, f"role:{role}"
        self._decide(now, "role_drain", rid,
                     f"phase imbalance prefill {p_press:.3f} vs decode "
                     f"{d_press:.3f}; role -> {role}")
        self._last_role = now
        return True

    # ------------------------------------------------------------- surface

    def marker(self) -> tuple:
        """Progress marker folded into the FleetSimulator's stall detector:
        scale decisions and ladder moves are progress even when no token
        moved this round."""
        rung = self.overload.rung if self.overload is not None else -1
        shed = self.overload.shed_count if self.overload is not None else 0
        return (len(self.decisions), rung, shed, self._draining,
                self._drain_mode)

    def wake_ts(self, now: float) -> Optional[float]:
        """Next instant a decision could possibly change — the simulator's
        idle-jump input while work is pending or a drain is in flight."""
        if self.router.outstanding == 0 and self._draining is None:
            return None
        base = self._last_eval if self._last_eval is not None else now
        return max(now, base + self.config.decide_interval)

    def finalize(self, now: float) -> None:
        if self.overload is not None:
            self.overload.finalize(now)

    def summary(self) -> dict:
        pool = self.pool
        return {
            "decisions": [list(d) for d in self.decisions],
            # a cancelled drain IS an up-capacity action (it emits
            # fleet/scale_up): capacity returned via restart, not recover
            "n_up": sum(1 for d in self.decisions
                        if d[1] in ("up", "drain_cancelled")),
            "n_down": sum(1 for d in self.decisions if d[1] == "down"),
            "ttft_ewma": None if self._ttft_ewma is None
            else round(self._ttft_ewma, 6),
            "provisioned_end": sum(
                1 for r in pool.rids
                if pool.health.state(r) is not ReplicaState.DEAD),
            "overload": None if self.overload is None
            else self.overload.summary(),
        }
