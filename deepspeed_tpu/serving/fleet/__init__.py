"""Fleet router: cache-affinity multi-replica serving with health-driven
failover (docs/SERVING.md "Fleet router").

One ``ServingEngine`` serves one mesh; the fleet layer is the data plane
above N of them: a :class:`ReplicaPool` (shared clock, health tracking,
kill/recover/drain lifecycle, per-replica :class:`ReplicaRole`\\ s for
prefill/decode disaggregation), a :class:`Router` with pluggable policies
(round-robin, least-outstanding-tokens, prefix-affinity with least-loaded
fallback, directory-resident ``prefix_directory`` with cold-replica
hot-prefix KV import, role-aware ``disaggregated`` with host-staged KV
migration — ``serving/kvtransfer``), a fleet-global
:class:`PrefixDirectory` replicas publish their prefix-chain digests
into, and a deterministic :class:`FleetSimulator` that
replays arrivals plus a scripted fault schedule bit-reproducibly on CPU
(``scripts/bench_router.py`` is the load harness; the seeded workload
generators live in :mod:`.sim`).
"""

from .autoscale import (RUNGS, AutoscaleConfig, Autoscaler, OverloadConfig,
                        OverloadController)
from .health import (FleetHealthView, HealthConfig, HealthTracker, LeaseConfig,
                     LeaseState, ReplicaState, classify_fatal)
from .policies import (POLICIES, DisaggregatedPolicy, LeastOutstandingPolicy,
                       PrefixAffinityPolicy, PrefixDirectoryPolicy,
                       RoundRobinPolicy, RoutingPolicy, SessionAffinityPolicy,
                       make_policy)
from .pool import Replica, ReplicaPool, ReplicaRole
from .prefix_directory import PrefixDirectory
from .router import FleetRequest, FleetState, Router
from .sim import (FleetEvent, FleetSimulator, diurnal_arrivals,
                  flash_crowd_arrivals, heavy_tail_arrivals,
                  poisson_mixed_arrivals, session_arrivals)
from .tenancy import DEFAULT_TENANT, TenantRegistry, TenantSpec
from .transport import (MESSAGE_KINDS, MESSAGE_VERSION, ControlTransport,
                        LinkFaults, Message, PartitionWindow)

__all__ = [
    "RUNGS", "AutoscaleConfig", "Autoscaler", "OverloadConfig",
    "OverloadController",
    "ControlTransport", "LinkFaults", "Message", "PartitionWindow",
    "MESSAGE_KINDS", "MESSAGE_VERSION",
    "FleetHealthView", "LeaseConfig", "LeaseState",
    "HealthConfig", "HealthTracker", "ReplicaState", "classify_fatal",
    "POLICIES", "DisaggregatedPolicy", "LeastOutstandingPolicy",
    "PrefixAffinityPolicy", "PrefixDirectoryPolicy", "PrefixDirectory",
    "RoundRobinPolicy", "RoutingPolicy", "SessionAffinityPolicy",
    "make_policy",
    "Replica", "ReplicaPool", "ReplicaRole", "FleetRequest", "FleetState",
    "Router", "FleetEvent", "FleetSimulator", "diurnal_arrivals",
    "flash_crowd_arrivals", "heavy_tail_arrivals", "poisson_mixed_arrivals",
    "session_arrivals",
    "DEFAULT_TENANT", "TenantRegistry", "TenantSpec",
]
