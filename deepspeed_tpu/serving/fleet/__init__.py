"""Fleet router: cache-affinity multi-replica serving with health-driven
failover (docs/SERVING.md "Fleet router").

One ``ServingEngine`` serves one mesh; the fleet layer is the data plane
above N of them: a :class:`ReplicaPool` (shared clock, health tracking,
kill/recover/drain lifecycle), a :class:`Router` with pluggable policies
(round-robin, least-outstanding-tokens, prefix-affinity with least-loaded
fallback), and a deterministic :class:`FleetSimulator` that replays
arrivals plus a scripted fault schedule bit-reproducibly on CPU
(``scripts/bench_router.py`` is the load harness).
"""

from .health import HealthConfig, HealthTracker, ReplicaState, classify_fatal
from .policies import (POLICIES, LeastOutstandingPolicy, PrefixAffinityPolicy,
                       RoundRobinPolicy, RoutingPolicy, make_policy)
from .pool import Replica, ReplicaPool
from .router import FleetRequest, FleetState, Router
from .sim import FleetEvent, FleetSimulator

__all__ = [
    "HealthConfig", "HealthTracker", "ReplicaState", "classify_fatal",
    "POLICIES", "LeastOutstandingPolicy", "PrefixAffinityPolicy",
    "RoundRobinPolicy", "RoutingPolicy", "make_policy",
    "Replica", "ReplicaPool", "FleetRequest", "FleetState", "Router",
    "FleetEvent", "FleetSimulator",
]
