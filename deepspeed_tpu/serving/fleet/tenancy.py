"""Multi-tenant QoS: per-tenant SLA budgets and weighted-fair ordering.

A fleet serving "millions of users" is never one user: traffic arrives
from TENANTS (products, API tiers, internal batch jobs) with different
contracts — a premium tier that pays for tight TTFT, a standard tier, and
best-effort bulk work that takes whatever is left.  This module is the
policy vocabulary the router threads through admission and dispatch:

* :class:`TenantSpec` — one tenant's contract: weighted-fair ``weight``
  (share of dispatch order under contention), ``max_outstanding``
  (concurrent dispatched requests — a heavy tenant's burst cannot occupy
  every replica slot), an optional ``ttft_slo`` (per-tenant violation
  accounting), and ``best_effort`` (eligible for the overload ladder's
  brownout caps and shedding — see :mod:`.autoscale`).
* :class:`TenantRegistry` — the spec table plus STRIDE-SCHEDULING state:
  each admitted request takes the tenant's current *pass* value and
  advances it by ``1 / weight``, so sorting pending requests by pass
  interleaves tenants in weight proportion.  A tenant with weight 4 gets
  ~4 dispatch slots for every 1 a weight-1 tenant gets while both are
  backlogged — and an idle tenant accumulates no credit: its pass is
  clamped up to the router's virtual-time floor (the minimum pass among
  pending requests) on (re)join, so a burst can neither bank unused share
  nor jump ahead of a backlog it sat out.

Everything here is plain deterministic arithmetic — no clocks, no RNG —
so the fleet's weighted-fair order is bit-identical across runs, which is
what lets the autoscale bench and the chaos suites pin byte-equal
dispatch sequences.
"""

import dataclasses
from typing import Dict, Iterable, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's QoS contract (see module docstring)."""
    name: str
    #: weighted-fair share under contention (higher = more dispatch slots);
    #: stride scheduling advances the tenant's pass by 1/weight per request
    weight: float = 1.0
    #: max concurrently DISPATCHED requests fleet-wide; <= 0 = unbounded
    max_outstanding: int = 0
    #: per-tenant TTFT budget for violation accounting (None = deadline-only)
    ttft_slo: Optional[float] = None
    #: eligible for brownout token caps and overload shedding
    best_effort: bool = False
    #: SLO error budget: the fraction of requests allowed to miss
    #: ``ttft_slo`` before the burn rate reads 1.0 — the denominator of
    #: the multi-window burn-rate monitor (telemetry/slo.py); unused when
    #: ``ttft_slo`` is None
    error_budget: float = 0.1
    #: fleet-wide KV arena budget in PAGES, metered by the exactly-once
    #: ``kv/tenant_pages/<tenant>`` attribution (Router.tenant_kv_pages):
    #: admission rejects a request whose projected page need would push
    #: the tenant past this bound (``fleet/kv_quota_reject`` + retry_after
    #: hint), and prefix-directory imports charge the importing tenant's
    #: budget before adopting remote pages; <= 0 = unbounded
    kv_page_quota: int = 0

    def __post_init__(self):
        if not self.weight > 0:
            raise ValueError(f"tenant {self.name!r}: weight must be > 0, "
                             f"got {self.weight}")
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if not 0.0 < self.error_budget <= 1.0:
            raise ValueError(f"tenant {self.name!r}: error_budget must be in "
                             f"(0, 1], got {self.error_budget}")
        if self.kv_page_quota < 0:
            raise ValueError(f"tenant {self.name!r}: kv_page_quota must be "
                             f">= 0 (0 = unbounded), got {self.kv_page_quota}")


#: the implicit tenant of untagged requests — weight 1, unbounded, not
#: best-effort: a tenant-less fleet behaves exactly like the pre-tenancy
#: router (pure FCFS within the single tenant)
DEFAULT_TENANT = TenantSpec(name="default")


class TenantRegistry:
    """Spec table + deterministic stride-scheduling pass state."""

    def __init__(self, specs: Iterable[TenantSpec] = ()):
        self._specs: Dict[str, TenantSpec] = {}
        #: tenant -> next stride pass (advanced by 1/weight per request)
        self._pass: Dict[str, float] = {}
        for s in specs:
            self.register(s)

    def register(self, spec: TenantSpec) -> TenantSpec:
        if spec.name in self._specs:
            raise ValueError(f"tenant {spec.name!r} already registered")
        self._specs[spec.name] = spec
        return spec

    def spec(self, name: str) -> TenantSpec:
        """Spec for ``name``; unknown tenants get an auto-created default
        contract (weight 1) — an unconfigured tenant is still served, just
        without privileges."""
        s = self._specs.get(name)
        if s is None:
            s = TenantSpec(name=name) if name != DEFAULT_TENANT.name \
                else DEFAULT_TENANT
            self._specs[name] = s
        return s

    def names(self) -> List[str]:
        return sorted(self._specs)

    def next_pass(self, name: str, floor: float = 0.0) -> float:
        """Take the tenant's current stride pass and advance it by
        ``1 / weight``.  ``floor`` is the caller's WFQ virtual time — the
        router passes the minimum pass among currently-pending requests —
        and the tenant's pass is clamped UP to it: a tenant joining (or
        rejoining after idling) competes from *now*, neither replaying the
        backlog it sat out nor spending banked credit to starve it."""
        spec = self.spec(name)
        p = max(self._pass.get(name, 0.0), floor)
        self._pass[name] = p + 1.0 / spec.weight
        return p

    def reset_passes(self) -> None:
        """Re-zero every tenant's stride state.  The router calls this when
        the fleet goes fully idle (no pending, no dispatched): with no
        backlog there is no share to arbitrate, and carrying old pass
        values into the next busy period would penalize past heavy users
        forever."""
        self._pass.clear()


def order_key(priority: float, wfq_pass: float, arrival_ts: float,
              fid: int) -> Tuple[float, float, float, int]:
    """The fleet pending-queue sort key: explicit priority class first
    (unchanged contract), then the weighted-fair stride pass, then FCFS.
    With a single tenant the pass is a submit-order counter, so the order
    degenerates to exactly the pre-tenancy (priority, arrival, fid)."""
    return (priority, wfq_pass, arrival_ts, fid)
